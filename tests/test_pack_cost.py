"""Cost model tests: compute-budget parsing, simple votes, fee math,
and the vote-cost block limit actually firing in the scheduler.

Pinned to the reference constants (src/disco/pack/fd_pack_cost.h,
fd_compute_budget_program.h) including the worked MAX_TXN_COST example
in the header comment."""
import pytest

from firedancer_tpu.pack import cost as pc
from firedancer_tpu.pack.scheduler import (PackLimits, PackScheduler,
                                           meta_from_payload)
from firedancer_tpu.protocol.txn import build_message, build_txn, parse_txn


def _payload(instrs, extra_accounts, n_signers=1, n_ro_unsigned=0,
             version=-1):
    signers = [bytes([0x40 + i]) * 32 for i in range(n_signers)]
    msg = build_message(signers, extra_accounts, b"\xbb" * 32, instrs,
                        n_ro_unsigned=n_ro_unsigned, version=version)
    return build_txn([b"\x01" * 64] * n_signers, msg)


def _cb_ix(kind: int, value: int, width: int = 4) -> bytes:
    return bytes([kind]) + value.to_bytes(width, "little")


def test_default_cost_no_compute_budget():
    # 1 signer + 1 writable + 1 non-builtin instr, 3 data bytes
    prog = b"\x77" * 32
    p = _payload([(2, bytes([1]), b"abc")],
                 [b"\x55" * 32, prog], n_ro_unsigned=1)
    t = parse_txn(p)
    tc = pc.compute_cost(t, p)
    assert not tc.is_simple_vote
    assert tc.execution == pc.DEFAULT_INSTR_CU_LIMIT
    assert tc.loaded_data_cost == 16384       # 64MiB/32KiB pages * 8
    assert tc.total == (720 + 2 * 300        # signer + 1 writable acct
                        + pc.DEFAULT_INSTR_CU_LIMIT
                        + 3 // 4 + 16384)
    assert tc.priority_fee == 0


def test_builtin_vs_non_builtin_default_cu():
    # one system-program (builtin: 3k) + one unknown program (200k)
    sysp = pc.SYSTEM_PROGRAM_ID
    unk = b"\x66" * 32
    p = _payload([(1, b"", b""), (2, b"", b"")], [sysp, unk])
    tc = pc.compute_cost(parse_txn(p), p)
    assert tc.execution == pc.MAX_BUILTIN_CU_LIMIT \
        + pc.DEFAULT_INSTR_CU_LIMIT


def test_set_compute_unit_limit_and_price():
    cb = pc.COMPUTE_BUDGET_PROGRAM_ID
    unk = b"\x66" * 32
    instrs = [(1, b"", _cb_ix(2, 500_000)),            # SetComputeUnitLimit
              (1, b"", _cb_ix(3, 2_000_000, 8)),       # SetComputeUnitPrice
              (2, b"", b"\x00" * 8)]
    p = _payload(instrs, [cb, unk])
    tc = pc.compute_cost(parse_txn(p), p)
    assert tc.execution == 500_000
    # ceil(500k CU * 2 lamports/CU-in-micro = 2e6 micro/CU / 1e6)
    assert tc.priority_fee == 1_000_000
    # CU limit clamps at 1.4M
    instrs[0] = (1, b"", _cb_ix(2, 100_000_000))
    p = _payload(instrs, [cb, unk])
    assert pc.compute_cost(parse_txn(p), p).execution == pc.MAX_CU_LIMIT


def test_loaded_accounts_data_size():
    cb = pc.COMPUTE_BUDGET_PROGRAM_ID
    p = _payload([(1, b"", _cb_ix(4, 33 * 1024))], [cb])
    tc = pc.compute_cost(parse_txn(p), p)
    assert tc.loaded_data_cost == 2 * pc.HEAP_COST    # 2 pages
    with pytest.raises(pc.CostError):                 # zero size invalid
        p = _payload([(1, b"", _cb_ix(4, 0))], [cb])
        pc.compute_cost(parse_txn(p), p)


def test_duplicate_and_malformed_compute_budget_fail():
    cb = pc.COMPUTE_BUDGET_PROGRAM_ID
    dup = [(1, b"", _cb_ix(2, 1000)), (1, b"", _cb_ix(2, 1000))]
    p = _payload(dup, [cb])
    with pytest.raises(pc.CostError):
        pc.compute_cost(parse_txn(p), p)
    p = _payload([(1, b"", b"\x02\x01")], [cb])       # too short
    with pytest.raises(pc.CostError):
        pc.compute_cost(parse_txn(p), p)
    p = _payload([(1, b"", _cb_ix(0, 5))], [cb])      # deprecated kind 0
    with pytest.raises(pc.CostError):
        pc.compute_cost(parse_txn(p), p)
    # heap size must be 1024-aligned
    p = _payload([(1, b"", _cb_ix(1, 1025))], [cb])
    with pytest.raises(pc.CostError):
        pc.compute_cost(parse_txn(p), p)


def test_precompile_signature_costs():
    ed = pc.ED25519_SV_PROGRAM_ID
    k1 = pc.KECCAK_SECP_PROGRAM_ID
    p = _payload([(1, b"", b"\x03" + b"\x00" * 10),   # 3 ed25519 sigs
                  (2, b"", b"\x02" + b"\x00" * 10)],  # 2 secp256k1 sigs
                 [ed, k1], n_ro_unsigned=2)
    tc = pc.compute_cost(parse_txn(p), p)
    assert tc.precompile_sig_cnt == 5
    base = 720 + 300                                  # 1 signer writable
    sig_extra = 3 * 2400 + 2 * 6690
    # both instrs are builtins -> 2*3000 CU
    assert tc.total == base + sig_extra + 6000 + 22 // 4 + 16384


def test_simple_vote_detection_and_fixed_cost():
    vote = pc.VOTE_PROGRAM_ID
    p = _payload([(2, bytes([1]), b"\x00" * 20)],
                 [b"\x11" * 32, vote])
    tc = pc.compute_cost(parse_txn(p), p)
    assert tc.is_simple_vote
    assert tc.total == pc.SIMPLE_VOTE_COST == 3428
    # v0 txns are never simple votes
    p = _payload([(2, bytes([1]), b"\x00" * 20)],
                 [b"\x11" * 32, vote], version=0)
    assert not pc.compute_cost(parse_txn(p), p).is_simple_vote


def test_scheduler_vote_limit_fires():
    """Votes beyond max_vote_cost_per_block are deferred even when the
    overall block limit has room (ref fd_pack vote limit)."""
    vote = pc.VOTE_PROGRAM_ID
    lim = PackLimits(max_vote_cost_per_block=2 * pc.SIMPLE_VOTE_COST + 1,
                     max_txn_per_microblock=10)
    sch = PackScheduler(bank_cnt=1, limits=lim)
    for i in range(4):
        signer = bytes([i + 1]) * 32
        msg = build_message([signer], [bytes([0x80 + i]) * 32, vote],
                            b"\xbb" * 32, [(2, bytes([1]), b"\x00" * 8)],
                            n_ro_unsigned=1)   # vote program readonly
        tid = sch.insert(meta_from_payload(build_txn([b"\x01" * 64], msg)))
        assert sch._pending[tid].is_vote
    mb = sch.schedule_microblock(0)
    assert len(mb) == 2                       # third vote exceeds limit
    assert all(m.is_vote for m in mb)
    assert sch.pending_cnt == 2


def test_reward_model_burn_and_priority():
    cb = pc.COMPUTE_BUDGET_PROGRAM_ID
    unk = b"\x66" * 32
    instrs = [(1, b"", _cb_ix(2, 1_000_000)),
              (1, b"", _cb_ix(3, 5_000_000, 8)),
              (2, b"", b"")]
    p = _payload(instrs, [cb, unk])
    m = meta_from_payload(p)
    # burned sig fee: 5000 * 1 sig * 50% = 2500; priority:
    # ceil(1M CU * 5 lamports/CU) = 5,000,000
    assert m.reward == 2500 + 5_000_000
    assert m.cost == pc.compute_cost(parse_txn(p), p).total


def test_max_txn_cost_bound():
    # the reference's worked bound: any txn cost fits under MAX_TXN_COST
    cb = pc.COMPUTE_BUDGET_PROGRAM_ID
    unk = b"\x66" * 32
    p = _payload([(1, b"", _cb_ix(2, pc.MAX_CU_LIMIT)), (2, b"", b"")],
                 [cb, unk], n_signers=9)
    tc = pc.compute_cost(parse_txn(p), p)
    assert tc.total < pc.MAX_TXN_COST
