"""fdgui v2 tests: shared ws plumbing, snapshot+delta protocol, slow-
client shedding, the live chaos acceptance drill, and the headless
report artifact (ref: src/disco/gui/fd_gui.c + fd_gui_tile.c protocol
shape, book/api/websocket.md; served by the shared waltz/http-style
plumbing in disco/httpd.py + disco/ws.py)."""
import base64
import glob
import hashlib
import json
import os
import socket
import struct
import time

import pytest

from firedancer_tpu.disco import Topology, TopologyRunner
from firedancer_tpu.disco.httpd import TileHttpServer
from firedancer_tpu.disco.ws import (OP_PING, OP_PONG, WsConn,
                                     encode_frame, read_frame)

gui = pytest.mark.gui
HERE = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# raw RFC 6455 test client (masked frames, blocking reads)
# ---------------------------------------------------------------------------

class WsTestClient:
    def __init__(self, port, path="/ws", rcvbuf=0, timeout=30,
                 origin=None):
        self.sock = socket.create_connection(("127.0.0.1", port),
                                             timeout=timeout)
        if rcvbuf:
            self.sock.setsockopt(socket.SOL_SOCKET, socket.SO_RCVBUF,
                                 rcvbuf)
        key = base64.b64encode(os.urandom(16)).decode()
        extra = f"Origin: {origin}\r\n" if origin else ""
        self.sock.sendall((
            f"GET {path} HTTP/1.1\r\nHost: x\r\nUpgrade: websocket\r\n"
            f"Connection: Upgrade\r\nSec-WebSocket-Key: {key}\r\n"
            f"{extra}Sec-WebSocket-Version: 13\r\n\r\n").encode())
        resp = b""
        while b"\r\n\r\n" not in resp:
            chunk = self.sock.recv(4096)
            assert chunk, f"server closed during handshake: {resp!r}"
            resp += chunk
        self.status = resp.split(b"\r\n")[0]
        if b"101" in self.status:
            want = base64.b64encode(hashlib.sha1(
                key.encode()
                + b"258EAFA5-E914-47DA-95CA-C5AB0DC85B11").digest())
            assert want in resp       # accept key verified (§4.2.2)

    def send_frame(self, payload: bytes, opcode=0x1):
        mask = os.urandom(4)
        masked = bytes(b ^ mask[i & 3] for i, b in enumerate(payload))
        hdr = bytes([0x80 | opcode])
        n = len(payload)
        assert n < 126
        self.sock.sendall(hdr + bytes([0x80 | n]) + mask + masked)

    def _exact(self, n):
        out = b""
        while len(out) < n:
            c = self.sock.recv(n - len(out))
            assert c, "peer closed"
            out += c
        return out

    def recv_frame(self):
        b0 = self._exact(2)
        op = b0[0] & 0x0F
        n = b0[1] & 0x7F
        if n == 126:
            n, = struct.unpack(">H", self._exact(2))
        elif n == 127:
            n, = struct.unpack(">Q", self._exact(8))
        return op, self._exact(n)

    def recv_json(self):
        op, payload = self.recv_frame()
        assert op == 0x1
        return json.loads(payload)

    def close(self):
        try:
            self.sock.close()
        except OSError:
            pass


# ---------------------------------------------------------------------------
# framing + handshake + queue policy units
# ---------------------------------------------------------------------------

@gui
def test_frame_codec_roundtrip_all_length_classes():
    """encode_frame/read_frame round-trip through the 7-bit, 16-bit
    and 64-bit length encodings, and masked client frames unmask."""
    a, b = socket.socketpair()
    try:
        for n in (0, 1, 125, 126, 1000, 1 << 16):
            payload = bytes(i & 0xFF for i in range(n))
            a.sendall(encode_frame(payload))
            op, got = read_frame(b)
            assert op == 0x1 and got == payload
        # masked client frame (the §5.1 requirement)
        mask = b"\x01\x02\x03\x04"
        payload = b"masked-hello"
        masked = bytes(c ^ mask[i & 3] for i, c in enumerate(payload))
        a.sendall(bytes([0x81, 0x80 | len(payload)]) + mask + masked)
        op, got = read_frame(b)
        assert got == payload
    finally:
        a.close()
        b.close()


@gui
def test_ws_upgrade_handshake_ping_and_client_limit():
    """TileHttpServer streaming routes: 101 upgrade with the computed
    accept key, on_connect document delivery, ping->pong, plain-GET
    routes still served, and the ws_max_clients 503 refusal."""
    srv = TileHttpServer(
        {"/x": lambda: (200, "text/plain", b"ok")},
        ws_routes={"/ws": lambda conn: conn.send_json({"hello": 1})},
        ws_max_clients=1, ws_queue=8)
    try:
        c1 = WsTestClient(srv.port)
        assert b"101" in c1.status
        assert c1.recv_json() == {"hello": 1}
        c1.send_frame(b"ka", opcode=OP_PING)
        op, payload = c1.recv_frame()
        assert op == OP_PONG and payload == b"ka"
        # plain HTTP still served next to the ws route
        import urllib.request
        assert urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/x", timeout=10).read() \
            == b"ok"
        # second concurrent client: refused with 503, not queued
        c2 = WsTestClient(srv.port)
        assert b"503" in c2.status
        c2.close()
        assert srv.ws_stats()["clients"] == 1
        c1.close()
        deadline = time.time() + 10
        while time.time() < deadline and srv.ws_stats()["clients"]:
            time.sleep(0.02)
        assert srv.ws_stats()["clients"] == 0
        # cross-origin browser pages are refused (WebSocket is exempt
        # from same-origin policy — without this, any website could
        # stream the operator dashboard off an operator's loopback);
        # loopback origins and non-browser clients (no Origin) pass
        c3 = WsTestClient(srv.port, origin="http://evil.example")
        assert b"403" in c3.status
        c3.close()
        c4 = WsTestClient(srv.port, origin="http://localhost:9999")
        assert b"101" in c4.status
        assert c4.recv_json() == {"hello": 1}
        c4.close()
    finally:
        srv.close()


@gui
def test_ws_queue_drop_oldest_then_shed_never_blocks():
    """The graceful-degradation contract: a stalled reader first costs
    itself dropped frames (drop-oldest past the high-water mark), then
    gets force-closed (shed) — and the enqueue side NEVER blocks, so
    the serving tile's housekeeping cadence is structurally immune."""
    a, b = socket.socketpair()
    try:
        conn = WsConn(a, hwm=4, sndbuf=4096)
        frame = encode_frame(b"x" * 2048)
        worst = 0.0
        for _ in range(200):
            t0 = time.perf_counter()
            conn.enqueue(frame)
            worst = max(worst, time.perf_counter() - t0)
            if conn.shed:
                break
            time.sleep(0.001)
        assert conn.shed, "stalled reader was never shed"
        assert conn.dropped > 4
        assert conn.closed
        # the bound that matters: no enqueue ever waited on the peer
        assert worst < 0.2, f"enqueue blocked for {worst:.3f}s"
    finally:
        a.close()
        b.close()


@gui
def test_ws_healthy_client_gets_everything_in_order():
    a, b = socket.socketpair()
    drain = []
    import threading
    def reader():
        try:
            while len(drain) < 50:
                op, payload = read_frame(b)
                drain.append(json.loads(payload))
        except (ConnectionError, OSError):
            pass
    t = threading.Thread(target=reader, daemon=True)
    t.start()
    try:
        conn = WsConn(a, hwm=64)
        for i in range(50):
            assert conn.send_json({"i": i})
        t.join(10)
        assert [d["i"] for d in drain] == list(range(50))
        assert conn.dropped == 0 and not conn.shed
        conn.close()
    finally:
        a.close()
        b.close()


# ---------------------------------------------------------------------------
# arg schema: the [trace]/[prof]-style three-layer contract
# ---------------------------------------------------------------------------

@gui
def test_gui_args_schema_and_registry_mirror():
    from firedancer_tpu.gui import GUI_DEFAULTS, normalize_gui
    from firedancer_tpu.lint.registry import TILE_ARGS
    # the lint/config registry mirrors the schema exactly
    assert set(TILE_ARGS["gui"]) == set(GUI_DEFAULTS)
    out = normalize_gui(None)
    assert out == GUI_DEFAULTS
    # common/structural keys pass through untouched
    normalize_gui({"supervise": {"policy": "restart"}, "ws_queue": 8})
    with pytest.raises(ValueError, match="did you mean 'ws_queue'"):
        normalize_gui({"ws_quee": 8})
    with pytest.raises(ValueError, match="ws_max_clients"):
        normalize_gui({"ws_max_clients": 0})
    with pytest.raises(ValueError, match="ws_queue"):
        normalize_gui({"ws_queue": 1})
    with pytest.raises(ValueError, match="tps_tile"):
        normalize_gui({"tps_tile": ""})
    # topo.build runs the same gate (fail before launch)
    bad = (Topology(f"gbad{os.getpid()}", wksp_size=1 << 20)
           .link("l", depth=16, mtu=64)
           .tile("s", "synth", outs=["l"], count=1)
           .tile("k", "sink", ins=["l"])
           .tile("g", "gui", ws_queue=0))
    with pytest.raises(ValueError, match="ws_queue"):
        bad.build()


# ---------------------------------------------------------------------------
# snapshot + delta protocol schema (in-process, no tile processes)
# ---------------------------------------------------------------------------

@gui
def test_snapshot_delta_schema_roundtrip():
    from firedancer_tpu.gui import (DeltaSource, cfg_digest,
                                    snapshot_doc)
    from firedancer_tpu.runtime import Workspace
    topo = (
        Topology(f"gs{os.getpid()}", wksp_size=1 << 21,
                 slo={"target": [{"name": "bp",
                                  "expr": "link.a_b.backpressure "
                                          "rate < 5/s"}]})
        .link("a_b", depth=32, mtu=256)
        .tile("a", "synth", outs=["a_b"], count=64, unique=8)
        .tile("b", "sink", ins=["a_b"])
        .tile("metric", "metric", port=0)
        .tile("gui", "gui", port=0)
    )
    plan = topo.build()
    wksp = Workspace(plan["wksp"]["name"], plan["wksp"]["size"],
                     create=False)
    try:
        snap = json.loads(json.dumps(snapshot_doc(plan)))
        assert snap["type"] == "snapshot" and snap["v"] == 2
        assert snap["cfg_digest"] == cfg_digest(plan)
        assert set(snap["tiles"]) == {"a", "b", "metric", "gui"}
        assert snap["tiles"]["b"]["ins"] == ["a_b"]
        assert snap["links"]["a_b"]["producer"] == "a"
        assert snap["links"]["a_b"]["consumers"] == ["b"]
        assert snap["links"]["a_b"]["depth"] == 32
        assert [t["name"] for t in snap["slo"]["targets"]] == ["bp"]
        src = DeltaSource(plan, wksp, tps_tile="b", tps_metric="rx")
        d = json.loads(json.dumps(src.delta()))
        assert d["type"] == "delta" and d["ts"] > 0
        assert set(d["tiles"]) == set(snap["tiles"])
        row = d["tiles"]["b"]
        for key in ("state", "hb_age_ticks", "metrics", "latency",
                    "occupancy"):
            assert key in row, row
        assert 0.0 <= row["occupancy"]["work"] <= 1.0
        assert "sup_restarts" in row["metrics"]   # supervisor counters
        assert set(d["links"]) == {"a_b"}
        for key in ("pub", "backpressure", "consumers"):
            assert key in d["links"]["a_b"]
        assert set(d["slo"]) >= {"breach", "breaches", "events"}
        # second delta: interval occupancy still in range
        d2 = src.delta()
        assert 0.0 <= d2["tiles"]["a"]["occupancy"]["work"] <= 1.0
    finally:
        wksp.close()
        Workspace.unlink_name(plan["wksp"]["name"])
        path = f"/dev/shm/fdtpu_{plan['topology']}.plan.json"
        if os.path.exists(path):
            os.unlink(path)


# ---------------------------------------------------------------------------
# live acceptance: chaos stall -> backpressure delta + SLO breach seen
# by a ws client; slow second client shed; cadence unperturbed;
# post-mortem report from the halted topology's shm
# ---------------------------------------------------------------------------

@gui
@pytest.mark.chaos
def test_live_chaos_ws_stream_shed_and_postmortem_report(tmp_path):
    topo = (
        Topology(f"gl{os.getpid()}", wksp_size=1 << 22,
                 trace={"enable": True, "depth": 512, "sample": 1,
                        "tiles": ["metric"]},
                 slo={"fast_window_s": 0.5, "slow_window_s": 10.0,
                      "target": [{
                          "name": "sink-bp",
                          "expr": "link.a_b.backpressure rate < 5/s"}]})
        .link("a_b", depth=32, mtu=256)
        .tile("a", "synth", outs=["a_b"], count=5_000_000, unique=16,
              burst=8)
        .tile("b", "sink", ins=["a_b"],
              chaos={"events": [{"action": "stall_fseq", "at_rx": 8}]})
        .tile("metric", "metric", port=0)
        .tile("gui", "gui", port=0, tps_tile="b", tps_metric="rx",
              ws_queue=8, ws_sndbuf=4096)
    )
    runner = TopologyRunner(topo.build()).start()
    try:
        runner.wait_running(timeout_s=540)
        deadline = time.time() + 30
        port = 0
        while time.time() < deadline and not port:
            runner.check_failures()
            port = int(runner.metrics("gui").get("port", 0))
            time.sleep(0.05)
        assert port
        # client A: healthy reader — snapshot first, then deltas
        ca = WsTestClient(port)
        snap = ca.recv_json()
        assert snap["type"] == "snapshot"
        assert snap["links"]["a_b"]["producer"] == "a"
        # client B: completes the handshake, then never reads again
        cb = WsTestClient(port, rcvbuf=4096)
        assert b"101" in cb.status
        saw_bp = saw_breach = False
        gaps = []
        last = time.time()
        deadline = time.time() + 60
        while time.time() < deadline and not (saw_bp and saw_breach):
            runner.check_failures()
            d = ca.recv_json()
            now = time.time()
            gaps.append(now - last)
            last = now
            assert d["type"] == "delta"
            if d["links"].get("a_b", {}).get("backpressure", 0) > 0:
                saw_bp = True
            slo = d.get("slo", {})
            if slo.get("breach", 0) >= 1 or any(
                    e.get("target") == "sink-bp"
                    for e in slo.get("events", [])):
                saw_breach = True
        assert saw_bp, "client never observed the backpressure delta"
        assert saw_breach, "client never observed the SLO breach"
        # the stalled client got shed; the healthy stream (above) kept
        # flowing the whole time — bounded overhead: the gui tile's
        # delta cadence never gapped anywhere near the slow client's
        # stall, and its heartbeat stayed fresh
        deadline = time.time() + 60
        shed = 0
        while time.time() < deadline and not shed:
            runner.check_failures()
            shed = runner.metrics("gui").get("ws_shed", 0)
            try:
                ca.recv_json()       # keep draining A
            except AssertionError:
                pass
            time.sleep(0.01)
        assert shed >= 1, "stalled client was never shed"
        assert max(gaps) < 5.0, f"delta stream stalled: {max(gaps):.1f}s"
        assert runner.heartbeats()["gui"] < int(5e9)
        # breach dump hygiene (written by the slo engine during the run)
        from firedancer_tpu.disco.slo import slo_dump_path
        dump = slo_dump_path(runner.plan["topology"], "sink-bp")
        ca.close()
        cb.close()
        # halt the topology, keep the shm: the report must render
        # POST-MORTEM from the workspace + plan alone
        runner.halt(join_timeout_s=10)
        from firedancer_tpu.gui.cli import main as gui_main
        out = tmp_path / "postmortem.html"
        rc = gui_main([runner.plan["topology"], "--report", str(out)])
        assert rc == 0
        html = out.read_text()
        assert "window.FDGUI_DATA" in html
        data = json.loads(
            html.split("window.FDGUI_DATA=", 1)[1]
            .split("</script>", 1)[0].replace("<\\/", "</"))
        assert data["snapshot"]["topology"] == runner.plan["topology"]
        final = data["deltas"][-1]
        assert final["links"]["a_b"]["backpressure"] > 0
        assert final["tiles"]["b"]["state"] in ("halt", "FAIL")
        if os.path.exists(dump):
            os.unlink(dump)          # test hygiene (/dev/shm)
    finally:
        runner.halt(join_timeout_s=10)
        runner.close()


# ---------------------------------------------------------------------------
# bench-trend report (the FDTPU_BENCH_REPORT artifact)
# ---------------------------------------------------------------------------

@gui
def test_report_from_bench_jsons(tmp_path):
    from firedancer_tpu.gui.report import bench_series, \
        report_from_bench
    paths = sorted(glob.glob(os.path.join(HERE, "BENCH_r0*.json")))
    assert len(paths) >= 2, "repo bench rounds missing"
    rows = bench_series(paths)
    assert len(rows) == len(paths)
    # early rounds may predate the record format — the chart renders
    # whatever rounds carry numbers, it never refuses the report
    assert sum(r["value"] is not None for r in rows) >= 2
    assert any(r["e2e_tps"] is not None for r in rows)
    out = tmp_path / "bench.html"
    report_from_bench(paths, str(out))
    html = out.read_text()
    assert "window.FDGUI_DATA" in html and "bench trends" in html
    data = json.loads(
        html.split("window.FDGUI_DATA=", 1)[1]
        .split("</script>", 1)[0].replace("<\\/", "</"))
    assert [r["file"] for r in data["bench"]] \
        == [os.path.basename(p) for p in paths]


@gui
def test_bench_py_emits_report_when_env_set(tmp_path, monkeypatch):
    """FDTPU_BENCH_REPORT wiring: bench.py's report hook writes the
    artifact next to the BENCH json with THIS round appended to the
    trajectory, and annotates the result record with its path."""
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "bench_under_test", os.path.join(HERE, "bench.py"))
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    out = tmp_path / "round.report.html"
    result = {"metric": "ed25519_verifies_per_sec", "value": 123456.0,
              "unit": "verifies/s/chip", "e2e_tps": 9999.0}
    monkeypatch.setenv("FDTPU_BENCH_REPORT", str(out))
    bench._emit_report(result)
    assert result.get("report") == str(out), result
    html = out.read_text()
    data = json.loads(
        html.split("window.FDGUI_DATA=", 1)[1]
        .split("</script>", 1)[0].replace("<\\/", "</"))
    # the current round rides at the end of the trajectory
    assert data["bench"][-1]["value"] == 123456.0
    assert data["bench"][-1]["e2e_tps"] == 9999.0
    # unset -> no-op
    monkeypatch.delenv("FDTPU_BENCH_REPORT")
    clean: dict = {}
    bench._emit_report(clean)
    assert clean == {}


@gui
def test_bench_only_cli_and_fdbench_report_links(tmp_path, capsys):
    from firedancer_tpu.gui.cli import main as gui_main
    out = tmp_path / "trend.html"
    rc = gui_main(["--bench", os.path.join(HERE, "BENCH_r0*.json"),
                   "--report", str(out)])
    assert rc == 0 and out.exists()
    capsys.readouterr()
    # fdbench names each round's report artifact when one exists
    import shutil

    from firedancer_tpu.prof.bench_diff import main as fdbench_main
    old = tmp_path / "BENCH_old.json"
    new = tmp_path / "BENCH_new.json"
    shutil.copy(os.path.join(HERE, "BENCH_r04.json"), old)
    shutil.copy(os.path.join(HERE, "BENCH_r05.json"), new)
    (tmp_path / "BENCH_old.report.html").write_text("x")
    rc = fdbench_main([str(old), str(new)])
    assert rc == 0
    text = capsys.readouterr().out
    assert "report (old):" in text and "BENCH_old.report.html" in text
