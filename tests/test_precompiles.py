"""Keccak / secp256k1 / precompile tests (ref: src/ballet/keccak256/,
src/ballet/secp256k1/, src/flamenco/runtime/fd_precompiles.c)."""
import struct

import pytest

from firedancer_tpu.funk.funk import Funk
from firedancer_tpu.protocol.txn import build_message, build_txn
from firedancer_tpu.svm import AccDb, Account, TxnExecutor
from firedancer_tpu.svm.precompiles import (
    ED25519_PROGRAM_ID, SECP256K1_PROGRAM_ID, THIS_IX,
)
from firedancer_tpu.svm.programs import ERR_VM, OK
from firedancer_tpu.utils import secp256k1 as secp
from firedancer_tpu.utils.ed25519_ref import keypair, sign
from firedancer_tpu.utils.keccak import keccak256


def k(n):
    return bytes([n]) * 32


def test_keccak_vectors():
    assert keccak256(b"").hex() == (
        "c5d2460186f7233c927e7db2dcc703c0e500b653ca82273b7bfad8045d85a470")
    assert keccak256(b"abc").hex() == (
        "4e03657aea45a94fc7d47ba826c8d667c0d1e6e33a64a036ec44f58fa12d6c45")
    assert keccak256(b"The quick brown fox jumps over the lazy dog"
                     ).hex() == ("4d741b6f1eb29cb2a9b9911c82f56fa8d73b0"
                                 "4959d3d9d222895df6c0b28aa15")
    # rate-boundary lengths
    for n in (135, 136, 137, 271, 272):
        assert len(keccak256(b"q" * n)) == 32


def test_secp_sign_verify_recover():
    priv = 0xC0FFEE1234567890C0FFEE1234567890C0FFEE1234567890C0FFEE12345678
    q = secp._mul(priv, (secp.GX, secp.GY))
    for i in range(4):
        h = keccak256(b"message-%d" % i)
        r, s, rec = secp.sign(priv, h)
        assert secp.verify(q, h, r, s)
        assert not secp.verify(q, keccak256(b"other"), r, s)
        got = secp.recover(h, r, s, rec)
        assert got == q
        assert secp.eth_address(got) == secp.eth_address(q)
    assert secp.recover(h, r, s, rec ^ 1) != q      # wrong parity


def _exec(txn_bytes):
    funk = Funk()
    db = AccDb(funk)
    funk.rec_write(None, k(1), Account(lamports=1_000_000))
    funk.txn_prepare(None, "blk")
    return TxnExecutor(db, enforce_rent=False).execute("blk", txn_bytes)


def _txn(program_id, ix_data):
    msg = build_message([k(1)], [program_id], b"\x11" * 32,
                        [(1, b"", ix_data)], n_ro_unsigned=1)
    return build_txn([bytes(64)], msg)


def _ed25519_ix(sig, pub, msg):
    hdr_sz = 2 + 14
    data = bytearray(bytes([1, 0]))
    data += struct.pack("<HHHHHHH", hdr_sz, THIS_IX,
                        hdr_sz + 64, THIS_IX,
                        hdr_sz + 96, len(msg), THIS_IX)
    data += sig + pub + msg
    return bytes(data)


def test_ed25519_precompile():
    seed = bytes(range(32))
    _, _, pub = keypair(seed)
    msg = b"precompile me"
    sig = sign(seed, msg)
    assert _exec(_txn(ED25519_PROGRAM_ID,
                      _ed25519_ix(sig, pub, msg))).status == OK
    bad = bytearray(sig)
    bad[0] ^= 1
    r = _exec(_txn(ED25519_PROGRAM_ID,
                   _ed25519_ix(bytes(bad), pub, msg)))
    assert r.status == ERR_VM


def _secp_ix(sig65, addr, msg):
    hdr_sz = 1 + 11
    data = bytearray(bytes([1]))
    data += struct.pack("<HBHBHHB", hdr_sz, 0xFF,
                        hdr_sz + 65, 0xFF,
                        hdr_sz + 85, len(msg), 0xFF)
    data += sig65 + addr + msg
    return bytes(data)


def test_secp256k1_precompile():
    priv = 0xD00D
    q = secp._mul(priv, (secp.GX, secp.GY))
    msg = b"ethereum-flavored auth"
    r, s, rec = secp.sign(priv, keccak256(msg))
    sig65 = r.to_bytes(32, "big") + s.to_bytes(32, "big") + bytes([rec])
    addr = secp.eth_address(q)
    assert _exec(_txn(SECP256K1_PROGRAM_ID,
                      _secp_ix(sig65, addr, msg))).status == OK
    # wrong address refused
    r2 = _exec(_txn(SECP256K1_PROGRAM_ID,
                    _secp_ix(sig65, bytes(20), msg)))
    assert r2.status == ERR_VM
    # truncated offsets refused, not crashed
    r3 = _exec(_txn(SECP256K1_PROGRAM_ID, bytes([3]) + bytes(5)))
    assert r3.status == "bad_instruction_data"


def _p256_ix(sig, pub33, msg):
    hdr_sz = 2 + 14
    data = bytearray(bytes([1, 0]))
    data += struct.pack("<HHHHHHH", hdr_sz, THIS_IX,
                        hdr_sz + 64, THIS_IX,
                        hdr_sz + 97, len(msg), THIS_IX)
    data += sig + pub33 + msg
    return bytes(data)


def test_secp256r1_precompile():
    """P-256 precompile (SIMD-0075): verify via an OpenSSL-made
    signature, reject corrupt/high-s/truncated."""
    pytest.importorskip("cryptography")
    from cryptography.hazmat.primitives.asymmetric import ec
    from cryptography.hazmat.primitives.asymmetric.utils import (
        decode_dss_signature)
    from cryptography.hazmat.primitives import hashes, serialization
    from firedancer_tpu.pack.cost import SECP256R1_PROGRAM_ID
    from firedancer_tpu.utils import secp256r1 as r1

    key = ec.generate_private_key(ec.SECP256R1())
    pub33 = key.public_key().public_bytes(
        serialization.Encoding.X962,
        serialization.PublicFormat.CompressedPoint)
    msg = b"p256 precompile"
    r, s = decode_dss_signature(key.sign(msg, ec.ECDSA(hashes.SHA256())))
    if s > r1.N // 2:
        s = r1.N - s
    sig = r.to_bytes(32, "big") + s.to_bytes(32, "big")
    assert _exec(_txn(SECP256R1_PROGRAM_ID,
                      _p256_ix(sig, pub33, msg))).status == OK
    bad = bytearray(sig)
    bad[5] ^= 1
    assert _exec(_txn(SECP256R1_PROGRAM_ID,
                      _p256_ix(bytes(bad), pub33, msg))).status == ERR_VM
    # high-s rejected (strict verifier)
    highs = r.to_bytes(32, "big") + (r1.N - s).to_bytes(32, "big")
    assert _exec(_txn(SECP256R1_PROGRAM_ID,
                      _p256_ix(highs, pub33, msg))).status == ERR_VM
    # truncated refused, not crashed
    assert _exec(_txn(SECP256R1_PROGRAM_ID,
                      bytes([2, 0]) + bytes(6))).status == \
        "bad_instruction_data"


def test_secp256r1_count_cap():
    """SIMD-0075: num_signatures must be 1..=8."""
    from firedancer_tpu.pack.cost import SECP256R1_PROGRAM_ID
    bad = bytes([9, 0]) + bytes(14 * 9)
    assert _exec(_txn(SECP256R1_PROGRAM_ID, bad)).status == \
        "bad_instruction_data"
    assert _exec(_txn(SECP256R1_PROGRAM_ID, bytes([0, 0]))).status == \
        "bad_instruction_data"
