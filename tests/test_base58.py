"""base58 tests (ref: src/ballet/base58/test_base58.c — fixed-size
32/64 vectors incl. leading zeros and boundary values)."""
import numpy as np
import pytest

from firedancer_tpu.utils.base58 import (
    b58_encode, b58_decode, b58_encode_32, b58_decode_32,
    b58_encode_64, b58_decode_64, ALPHABET)


def test_known_values():
    # the system program address: 32 zero bytes -> 32 '1's
    assert b58_encode_32(bytes(32)) == "1" * 32
    assert b58_decode_32("1" * 32) == bytes(32)
    assert b58_encode(b"") == ""
    assert b58_decode("", 0) == b""
    # single bytes
    assert b58_encode(b"\x00") == "1"
    assert b58_encode(b"\x39") == "z"   # 57 -> last alphabet char
    assert b58_encode(b"\x3a") == "21"  # 58 -> "21"
    assert b58_encode(b"\xff") == "5Q"  # 255 = 4*58+23 -> '5','Q'


def test_alphabet_excludes_ambiguous():
    assert len(ALPHABET) == 58
    for c in "0OIl":
        assert c not in ALPHABET


@pytest.mark.parametrize("size,enc,dec", [
    (32, b58_encode_32, b58_decode_32),
    (64, b58_encode_64, b58_decode_64),
])
def test_roundtrip_fixed(size, enc, dec):
    rng = np.random.default_rng(size)
    for _ in range(50):
        data = rng.integers(0, 256, size, dtype=np.uint8).tobytes()
        assert dec(enc(data)) == data
    # leading zeros preserved
    data = bytes(5) + rng.integers(0, 256, size - 5,
                                   dtype=np.uint8).tobytes()
    s = enc(data)
    assert s.startswith("1" * 5)
    assert dec(s) == data
    # all 0xff (boundary)
    assert dec(enc(b"\xff" * size)) == b"\xff" * size


def test_decode_rejects_invalid():
    with pytest.raises(ValueError):
        b58_decode("0")          # not in alphabet
    with pytest.raises(ValueError):
        b58_decode("I")          # ambiguous char excluded
    with pytest.raises(ValueError):
        b58_decode_32("z" * 44)  # too large for 32 bytes


def test_ordering_independent_impl():
    """Cross-check vs an independently-coded digit-by-digit decoder."""
    rng = np.random.default_rng(1)
    for _ in range(20):
        data = rng.integers(0, 256, 32, dtype=np.uint8).tobytes()
        s = b58_encode(data)
        # Horner re-encode check: rebuild integer from chars
        v = 0
        for c in s:
            v = v * 58 + ALPHABET.index(c)
        assert v == int.from_bytes(data, "big")
