"""alt-bn128 (utils/bn254.py) + the sol_alt_bn128_group_op syscall
(ref: src/ballet/bn254/, src/flamenco/vm/syscall/). Gates are
mathematical: generator membership, group laws, bilinearity — a wrong
Miller loop or final exponentiation cannot satisfy them."""
import pytest

from firedancer_tpu.utils import bn254 as bn


def test_generators_valid():
    assert bn.g1_on_curve(bn.G1_GEN)
    assert bn.g1_mul(bn.R, bn.G1_GEN) is None        # order r
    assert bn.g2_in_subgroup(bn.G2_GEN)
    # the untwist embedding lands on E(Fp12): y^2 = x^3 + 3
    x12, y12 = bn._embed_g2(bn.G2_GEN)
    lhs = bn.f12_mul(y12, y12)
    rhs = bn._f12_add(bn.f12_mul(bn.f12_mul(x12, x12), x12),
                      bn._f12_from_fp(3))
    assert lhs == rhs


def test_g1_group_laws():
    g = bn.G1_GEN
    assert bn.g1_add(bn.g1_mul(3, g), bn.g1_mul(4, g)) == bn.g1_mul(7, g)
    assert bn.g1_add(g, bn.g1_neg(g)) is None
    assert bn.g1_add(None, g) == g
    assert bn.g1_mul(0, g) is None


def test_pairing_bilinearity_and_nondegeneracy():
    g1, g2 = bn.G1_GEN, bn.G2_GEN
    # e(2P, 3Q) * e(-6P, Q) == 1
    assert bn.pairing_check([(bn.g1_mul(2, g1), bn.g2_mul(3, g2)),
                             (bn.g1_neg(bn.g1_mul(6, g1)), g2)])
    # e(aP, Q) * e(-P, aQ) == 1 for another exponent
    a = 11
    assert bn.pairing_check([(bn.g1_mul(a, g1), g2),
                             (bn.g1_neg(g1), bn.g2_mul(a, g2))])
    # non-degenerate: a single real pairing is NOT 1
    assert not bn.pairing_check([(g1, g2)])
    # infinity entries contribute identity
    assert bn.pairing_check([(None, g2), (g1, None)])
    assert bn.pairing_check([])


def test_eip196_serialization_and_ops():
    g = bn.G1_GEN
    two_g = bn.g1_mul(2, g)
    data = bn.enc_g1(g) + bn.enc_g1(g)
    assert bn.dec_g1(bn.alt_bn128_add(data)) == two_g
    mul_in = bn.enc_g1(g) + (5).to_bytes(32, "big")
    assert bn.dec_g1(bn.alt_bn128_mul(mul_in)) == bn.g1_mul(5, g)
    # infinity round trip
    assert bn.dec_g1(bytes(64)) is None
    assert bn.enc_g1(None) == bytes(64)
    # off-curve rejected
    with pytest.raises(ValueError):
        bn.dec_g1((1).to_bytes(32, "big") + (1).to_bytes(32, "big"))


def _enc_g2(pt):
    (xr, xi), (yr, yi) = pt
    return (xi.to_bytes(32, "big") + xr.to_bytes(32, "big")
            + yi.to_bytes(32, "big") + yr.to_bytes(32, "big"))


def test_eip197_pairing_precompile_format():
    g1, g2 = bn.G1_GEN, bn.G2_GEN
    good = (bn.enc_g1(bn.g1_mul(2, g1)) + _enc_g2(bn.g2_mul(3, g2))
            + bn.enc_g1(bn.g1_neg(bn.g1_mul(6, g1))) + _enc_g2(g2))
    assert bn.alt_bn128_pairing(good)[-1] == 1
    bad = bn.enc_g1(g1) + _enc_g2(g2)
    assert bn.alt_bn128_pairing(bad)[-1] == 0
    with pytest.raises(ValueError):
        bn.alt_bn128_pairing(b"\x00" * 100)     # not a 192 multiple


def test_syscall_roundtrip():
    from firedancer_tpu.vm import Vm
    from firedancer_tpu.vm.interp import INPUT_START
    from firedancer_tpu.vm.syscalls import (ALT_BN128_ADD,
                                            ALT_BN128_MUL,
                                            ALT_BN128_PAIRING,
                                            ALT_BN128_SUB,
                                            sys_alt_bn128_group_op)
    g = bn.G1_GEN
    inp = bn.enc_g1(g) + bn.enc_g1(g)
    vm = Vm(b"\x95" + bytes(7), input_data=inp + bytes(256))
    vm._cu = 0
    vm.compute_budget = 10_000_000
    out_addr = INPUT_START + 128
    rc = sys_alt_bn128_group_op(vm, ALT_BN128_ADD, INPUT_START, 128,
                                out_addr, 0)
    assert rc == 0
    assert bn.dec_g1(vm.mem_read(out_addr, 64)) == bn.g1_mul(2, g)
    # SUB: 2g - g = g
    vm.mem_write(INPUT_START, vm.mem_read(out_addr, 64) + bn.enc_g1(g))
    rc = sys_alt_bn128_group_op(vm, ALT_BN128_SUB, INPUT_START, 128,
                                out_addr, 0)
    assert rc == 0 and bn.dec_g1(vm.mem_read(out_addr, 64)) == g
    # MUL
    vm.mem_write(INPUT_START, bn.enc_g1(g) + (7).to_bytes(32, "big"))
    rc = sys_alt_bn128_group_op(vm, ALT_BN128_MUL, INPUT_START, 96,
                                out_addr, 0)
    assert rc == 0
    assert bn.dec_g1(vm.mem_read(out_addr, 64)) == bn.g1_mul(7, g)
    # PAIRING verdict
    good = (bn.enc_g1(bn.g1_mul(2, g)) + _enc_g2(bn.g2_mul(3, bn.G2_GEN))
            + bn.enc_g1(bn.g1_neg(bn.g1_mul(6, g))) + _enc_g2(bn.G2_GEN))
    vm.mem_write(INPUT_START, good)
    rc = sys_alt_bn128_group_op(vm, ALT_BN128_PAIRING, INPUT_START,
                                len(good), out_addr, 0)
    assert rc == 0 and vm.mem_read(out_addr, 32)[-1] == 1
    # malformed input -> r0=1, no crash
    vm.mem_write(INPUT_START, b"\x01" * 64 + bytes(64))
    rc = sys_alt_bn128_group_op(vm, ALT_BN128_ADD, INPUT_START, 128,
                                out_addr, 0)
    assert rc == 1


def test_noncanonical_and_oversize_rejected():
    """r4 review: coordinates >= P and oversized inputs must error
    like the reference, not silently reduce/truncate."""
    g = bn.G1_GEN
    # G2 coordinate + P: same point mod P but non-canonical encoding
    (xr, xi), (yr, yi) = bn.G2_GEN
    bad = ((xi + bn.P).to_bytes(32, "big") + xr.to_bytes(32, "big")
           + yi.to_bytes(32, "big") + yr.to_bytes(32, "big"))
    with pytest.raises(ValueError, match="canonical"):
        bn.dec_g2(bad)
    # oversized add/mul inputs
    with pytest.raises(ValueError, match="exceeds"):
        bn.alt_bn128_add(bytes(192))
    with pytest.raises(ValueError, match="exceeds"):
        bn.alt_bn128_mul(bytes(100))
    with pytest.raises(ValueError, match="exceeds"):
        bn.alt_bn128_sub(bytes(129))
    # sub helper semantics
    data = bn.enc_g1(bn.g1_mul(9, g)) + bn.enc_g1(bn.g1_mul(4, g))
    assert bn.dec_g1(bn.alt_bn128_sub(data)) == bn.g1_mul(5, g)
