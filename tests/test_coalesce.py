"""Staged device transfers + adaptive microbatch coalescing (r10).

Three contracts from the kernel<->pipeline gap work:

  * double-buffered staging: each dispatch is ONE transfer of the
    packed staging buffer, and dispatching batch k+1 never blocks on
    batch k's readback (scripted-future fake backend, the same style
    as the chaos degraded-path tests);
  * coalescing window: sub-full gathers are held until the lane budget
    fills, the deadline expires, or ingest idles with nothing in
    device flight — and held frags are never dropped or reordered;
  * drain-on-idle: batches already in device flight retire when ingest
    goes quiet mid-coalesce (queued verdicts never wait on traffic).
"""
import os

import numpy as np
import pytest

from firedancer_tpu.runtime import Ring, Tcache, Workspace
from firedancer_tpu.tiles.synth import make_signed_txns
from firedancer_tpu.tiles.verify import VerifyTile

pytestmark = pytest.mark.coalesce

BATCH = 16


@pytest.fixture(scope="module")
def wksp():
    w = Workspace(f"/fdtpu_co_{os.getpid()}", 1 << 24)
    yield w
    w.close()
    w.unlink()


@pytest.fixture(scope="module")
def txns():
    return make_signed_txns(24, seed=3)


@pytest.fixture(scope="module")
def _shared_tile(wksp):
    """ONE compiled VerifyTile for the whole module (tile warmup
    traces+compiles the packed verify jit — ~30 s on the 1-core CI
    box; per-test tiles would blow the tier-1 budget). Tests get it
    re-pointed at fresh rings/tcache via _mk_tile."""
    tile = VerifyTile(Ring.create(wksp, depth=256, mtu=1280),
                      Ring.create(wksp, depth=256, mtu=1280),
                      Tcache(wksp, depth=512), batch=BATCH,
                      coalesce_us=1.0)        # hold buffer allocated
    tile._real_fn = tile._fn
    return tile


def _mk_tile(wksp, coalesce_us: float = 0.0, _tile=None, **kw):
    """Reset the shared tile onto fresh rings + tcache with the given
    coalescing window — state-equivalent to a new VerifyTile without
    re-tracing the jit."""
    from collections import deque
    tile = _tile
    in_ring = Ring.create(wksp, depth=256, mtu=1280)
    out_ring = Ring.create(wksp, depth=256, mtu=1280)
    tile.in_ring, tile.out_ring = in_ring, out_ring
    tile.tcache = Tcache(wksp, depth=512)
    tile.seq = 0
    tile._fn = tile._real_fn
    tile._pending = deque()
    tile._bufset_fut = [None] * len(tile._bufsets)
    tile._disp = 0
    tile._deferred, tile._deferred_n = {}, 0
    tile.degraded, tile._consec_fail = False, 0
    tile.metrics = {k: 0 for k in tile.metrics}
    tile._coalesce_ns = max(0, int(float(coalesce_us) * 1e3))
    tile._hold_n, tile._hold_deadline = 0, 0
    return tile, in_ring, out_ring


def _collect(out_ring):
    got, seq = [], 0
    while True:
        rc, frag = out_ring.consume(seq)
        if rc != 0:
            break
        got.append(bytes(out_ring.payload(frag)))
        seq += 1
    return got


class ScriptedFut:
    """Fake device verdict future: is_ready() is test-scripted (a
    manual flag, or auto-ready after N polls — a device whose verdicts
    land mid-wait), and forcing it before the script says ready is the
    failure the staging contract forbids."""

    def __init__(self, verdicts):
        self.v = np.asarray(verdicts, bool)
        self.ready = False
        self.ready_after = None          # is_ready calls until ready
        self.polls = 0
        self.forced = 0

    def is_ready(self):
        self.polls += 1
        if self.ready_after is not None and self.polls >= self.ready_after:
            self.ready = True
        return self.ready

    def __array__(self, dtype=None, copy=None):
        assert self.ready, "verdict readback forced before scripted ready"
        self.forced += 1
        return self.v


def _script_backend(tile):
    """Swap the tile's jit for a scripted fake AFTER warmup: records
    each dispatch's transfer shape and returns a ScriptedFut."""
    futs = []
    shapes = []
    flat_len = tile._bufsets[0].flat.shape[0]

    def fake_fn(flat):
        shapes.append(tuple(np.asarray(flat).shape))
        fut = ScriptedFut(np.ones(tile.batch, bool))
        futs.append(fut)
        return fut

    tile._fn = fake_fn
    return futs, shapes, flat_len


def test_dispatch_k1_does_not_block_on_readback_of_k(wksp, txns, _shared_tile):
    """Acceptance: with two batches' verdicts scripted unresolved, the
    second dispatch completes without forcing the first readback, each
    dispatch ships exactly ONE packed transfer (the whole staging
    buffer), and verdicts retire oldest-first once ready."""
    tile, in_ring, out_ring = _mk_tile(wksp, _tile=_shared_tile)
    assert tile.inflight >= 2
    futs, shapes, flat_len = _script_backend(tile)

    for t in txns[:4]:
        in_ring.publish(t, sig=1)
    tile.poll_once()                      # batch k dispatched
    assert len(futs) == 1 and len(tile._pending) == 1
    for t in txns[4:8]:
        in_ring.publish(t, sig=2)
    tile.poll_once()                      # batch k+1: must not block
    assert len(futs) == 2 and len(tile._pending) == 2
    assert futs[0].forced == 0            # k's readback never forced
    # single staged transfer per dispatch: the packed flat buffer
    # (len|sig|pub|msg lanes back to back), not four per-array copies
    assert shapes == [(flat_len,), (flat_len,)]
    for f in futs:
        f.ready = True
    tile.flush()
    assert not tile._pending
    assert tile.metrics["tx"] == 8
    assert _collect(out_ring) == [bytes(t) for t in txns[:8]]


def test_coalesce_holds_subfull_until_lane_budget_fills(wksp, txns, _shared_tile):
    """With a long window, a sub-full gather dispatches nothing; the
    window flushes the instant the lane budget (one compiled batch)
    fills, with held + new frags forwarded in order."""
    tile, in_ring, out_ring = _mk_tile(wksp, coalesce_us=10_000_000, _tile=_shared_tile)
    for t in txns[:5]:
        in_ring.publish(t, sig=1)
    assert tile.poll_once() == 5          # consumed...
    assert tile.metrics["batches"] == 0   # ...but held, not dispatched
    assert tile._hold_n == 5
    for t in txns[5:16]:
        in_ring.publish(t, sig=2)
    tile.poll_once()                      # 5 + 11 == BATCH: flush
    assert tile._hold_n == 0
    assert tile.metrics["batches"] >= 1
    tile.flush()
    assert _collect(out_ring) == [bytes(t) for t in txns[:16]]


def test_coalesce_flushes_on_idle_when_device_idle(wksp, txns, _shared_tile):
    """Idle ingest with NO batch in device flight flushes the hold
    immediately — an idle device is never kept waiting for a fuller
    batch, whatever the deadline says."""
    tile, in_ring, out_ring = _mk_tile(wksp, coalesce_us=10_000_000, _tile=_shared_tile)
    for t in txns[:3]:
        in_ring.publish(t, sig=1)
    tile.poll_once()
    assert tile._hold_n == 3 and not tile._pending
    tile.poll_once()                      # idle poll: flush now
    assert tile._hold_n == 0 and tile.metrics["batches"] == 1
    tile.flush()
    assert tile.metrics["tx"] == 3


def test_coalesce_deadline_flush_under_trickle(wksp, txns, _shared_tile):
    """Trickling ingest never goes idle, so the DEADLINE is what bounds
    held-frag latency: once it expires the window dispatches even
    sub-full."""
    import time
    tile, in_ring, out_ring = _mk_tile(wksp, coalesce_us=50_000, _tile=_shared_tile)
    in_ring.publish(txns[0], sig=1)
    tile.poll_once()
    in_ring.publish(txns[1], sig=2)
    tile.poll_once()                      # trickle: still inside window
    assert tile.metrics["batches"] == 0 and tile._hold_n == 2
    time.sleep(0.06)                      # cross the 50 ms deadline
    in_ring.publish(txns[2], sig=3)
    tile.poll_once()
    assert tile.metrics["batches"] == 1 and tile._hold_n == 0
    tile.flush()
    assert _collect(out_ring) == [bytes(t) for t in txns[:3]]


def test_drain_on_idle_retires_inflight_mid_coalesce(wksp, txns, _shared_tile):
    """Ingest goes quiet while a window is held AND a batch is in
    device flight: the idle poll must retire the in-flight batch
    (drain-on-idle — queued verdicts never wait for more traffic), and
    must NOT flush the held window while the device is busy and the
    deadline is live."""
    tile, in_ring, out_ring = _mk_tile(wksp, coalesce_us=10_000_000, _tile=_shared_tile)
    futs, _, _ = _script_backend(tile)
    for t in txns[:16]:                   # fill one lane budget
        in_ring.publish(t, sig=1)
    tile.poll_once()                      # dispatches batch A
    assert len(tile._pending) == 1
    for t in txns[16:19]:
        in_ring.publish(t, sig=2)
    tile.poll_once()                      # sub-full window held
    assert tile._hold_n == 3
    # A's verdicts land only mid-wait: the idle poll's snapshot order
    # is checked — hold NOT flushed (device busy, deadline live), but
    # the in-flight batch still retires before the poll returns
    futs[0].ready_after = futs[0].polls + 2
    tile.poll_once()                      # IDLE: A retires, hold stays
    assert not tile._pending              # drain-on-idle
    assert tile._hold_n == 3              # device was busy: hold lives
    assert tile.metrics["tx"] == 16
    tile.poll_once()                      # idle again, device now idle
    assert tile._hold_n == 0
    for f in futs:
        f.ready = True
    tile.flush()
    assert _collect(out_ring) == [bytes(t) for t in txns[:19]]


def test_flush_dispatches_held_window_on_halt(wksp, txns, _shared_tile):
    """The halt path must not drop held ingest: flush() dispatches the
    window and retires it."""
    tile, in_ring, out_ring = _mk_tile(wksp, coalesce_us=10_000_000, _tile=_shared_tile)
    for t in txns[:7]:
        in_ring.publish(t, sig=1)
    tile.poll_once()
    assert tile._hold_n == 7
    tile.flush()
    assert tile._hold_n == 0 and not tile._pending
    assert _collect(out_ring) == [bytes(t) for t in txns[:7]]


def test_publish_batch_backpressure_resume(wksp):
    """Ring.publish_batch under a slow reliable consumer: stop_row < n
    means credits ran out; the producer heartbeats, the consumer
    advances its fseq a little, and the publish RESUMES from stop_row —
    across several stalls — with every masked row delivered exactly
    once, in order, byte-identical."""
    from firedancer_tpu.runtime import Fseq
    ring = Ring.create(wksp, depth=4, mtu=128)
    fs = Fseq(wksp)
    n = 11
    buf = np.zeros((n, 128), np.uint8)
    for i in range(n):
        buf[i, :8] = i + 1
    sizes = np.full(n, 8, np.uint32)
    sigs = np.arange(n, dtype=np.uint64)
    mask = np.ones(n, np.uint8)
    mask[2] = 0                          # hole must not publish
    start = pub_total = 0
    seq = 0
    got = []
    rounds = 0
    while start < n:
        start, pub = ring.publish_batch(buf, sizes, sigs, mask,
                                        fseqs=[fs], start=start)
        pub_total += pub
        rounds += 1
        assert rounds < 32               # no livelock
        # consumer side: drain what's there, publish progress (the
        # "heartbeat" step between stalls)
        while True:
            rc, frag = ring.consume(seq)
            if rc != 0:
                break
            got.append(bytes(ring.payload(frag)))
            seq += 1
        fs.update(seq)
    assert rounds > 1                    # backpressure actually engaged
    assert pub_total == n - 1
    while True:
        rc, frag = ring.consume(seq)
        if rc != 0:
            break
        got.append(bytes(ring.payload(frag)))
        seq += 1
    want = [bytes(buf[i, :8]) for i in range(n) if mask[i]]
    assert got == want
