"""Tile supervision v2: restart policies, ring rejoin, wedge watchdog,
circuit breaker (disco/supervise.py).

Recovery invariants asserted here (ISSUE r6 acceptance): a seeded tile
crash ends with the topology RECOVERED under a restart policy (bounded
restarts, producer never wedges — the dead consumer's fseq is marked
stale so fctl excludes it) or CLEANLY HALTED under fail_fast / an open
circuit breaker — never wedged; supervisor counters are observable via
the same metrics surfaces as tile counters.
"""
import os
import time

import pytest

from firedancer_tpu.disco import Topology, TopologyRunner
from firedancer_tpu.disco.supervise import (
    SUP_SLOT_MIN, SUP_SLOTS, CircuitOpen, normalize_policy,
)

pytestmark = pytest.mark.chaos

N = 600

# Watchdog window for live drills: on a 2-core CI box the scheduler
# can deschedule a HEALTHY tile long enough to trip a 0.4 s heartbeat
# deadline spuriously (the r10 tier-1 flake — extra restarts open the
# circuit breaker mid-test). Scale the window with the machine's
# actual parallelism instead of hoping the wall clock cooperates.
WEDGE_S = 0.4 if (os.cpu_count() or 1) >= 4 else 2.0


# -- policy plumbing (no processes) -----------------------------------------

def test_policy_normalization_defaults_and_validation():
    d = normalize_policy(None)
    assert d["policy"] == "fail_fast" and d["wedge_timeout_s"] is None
    r = normalize_policy({"policy": "restart", "max_restarts": 5,
                          "wedge_timeout_s": 2})
    assert r["max_restarts"] == 5 and r["wedge_timeout_s"] == 2.0
    with pytest.raises(ValueError, match="policy"):
        normalize_policy({"policy": "reboot"})
    with pytest.raises(ValueError, match="unknown supervise keys"):
        normalize_policy({"polcy": "restart"})
    with pytest.raises(ValueError, match="max_restarts"):
        normalize_policy({"max_restarts": 0})
    with pytest.raises(ValueError, match="wedge_timeout_s"):
        normalize_policy({"wedge_timeout_s": -1})


def test_supervisor_slots_clear_of_every_tile_kind():
    """No registered adapter may declare enough metric slots to collide
    with the supervisor-owned top slots."""
    from firedancer_tpu.disco.tiles import REGISTRY
    assert min(SUP_SLOTS.values()) == SUP_SLOT_MIN
    for kind, cls in REGISTRY.items():
        assert len(getattr(cls, "METRICS", [])) <= SUP_SLOT_MIN, kind


def test_policy_lands_in_plan_and_bad_policy_fails_build():
    topo = (Topology(f"pp{os.getpid()}", wksp_size=1 << 20)
            .link("a_b", depth=16, mtu=256)
            .tile("a", "synth", outs=["a_b"], count=4)
            .tile("b", "sink", ins=["a_b"],
                  supervise={"policy": "restart", "backoff_s": 0.1}))
    plan = topo.build()
    try:
        assert plan["tiles"]["b"]["supervise"]["policy"] == "restart"
        assert plan["tiles"]["a"]["supervise"]["policy"] == "fail_fast"
    finally:
        from firedancer_tpu.runtime import Workspace
        Workspace.unlink_name(plan["wksp"]["name"])
    bad = (Topology(f"pb{os.getpid()}", wksp_size=1 << 20)
           .link("a_b", depth=16, mtu=256)
           .tile("a", "synth", outs=["a_b"], count=4)
           .tile("b", "sink", ins=["a_b"], supervise={"policy": "nope"}))
    with pytest.raises(ValueError, match="policy"):
        bad.build()


# -- live topologies --------------------------------------------------------

def _run_until(runner, cond, timeout_s=90.0, poll_s=0.02):
    t0 = time.time()
    while time.time() - t0 < timeout_s:
        runner.check_failures()         # one supervision pass
        if cond():
            return
        time.sleep(poll_s)
    raise TimeoutError("condition never reached")


def test_crash_restart_and_ring_rejoin():
    """Sink crashes mid-stream (seeded chaos); restart policy respawns
    it, its stale fseq keeps the producer flowing, and the respawn
    rejoins at the ring tail — the producer finishes every send."""
    topo = (
        Topology(f"sc{os.getpid()}", wksp_size=1 << 22)
        .link("a_b", depth=32, mtu=256)
        .tile("a", "synth", outs=["a_b"], count=N, unique=16, burst=8)
        .tile("b", "sink", ins=["a_b"],
              supervise={"policy": "restart", "backoff_s": 0.05,
                         "max_restarts": 3, "window_s": 30.0},
              chaos={"seed": 1,
                     "events": [{"action": "crash", "at_rx": 24}]})
    )
    runner = TopologyRunner(topo.build()).start()
    try:
        runner.wait_running(timeout_s=540)
        _run_until(runner, lambda: runner.metrics("a")["tx"] >= N
                   and runner.metrics("b")["sup_restarts"] >= 1
                   and runner.metrics("b")["sup_down"] == 0)
        a, b = runner.metrics("a"), runner.metrics("b")
        assert a["tx"] == N                   # producer never wedged
        assert 1 <= b["sup_restarts"] <= 3    # bounded restarts
        # recovered: the respawned sink is alive again (rejoined at the
        # ring tail; frags published while down are the documented loss)
        assert runner.procs["b"].is_alive()
        assert b["rx"] <= N
    finally:
        runner.halt(join_timeout_s=10)
        runner.close()


def test_watchdog_trips_on_frozen_heartbeat():
    """A live-but-wedged tile (heartbeats frozen by chaos) is detected
    by the wedge watchdog, failed, killed, and restarted; the trip is
    observable in metrics."""
    topo = (
        Topology(f"sw{os.getpid()}", wksp_size=1 << 22)
        .link("a_b", depth=32, mtu=256)
        .tile("a", "synth", outs=["a_b"], count=N, unique=16, burst=8)
        .tile("b", "sink", ins=["a_b"],
              supervise={"policy": "restart", "backoff_s": 0.05,
                         "max_restarts": 4, "window_s": 30.0,
                         "wedge_timeout_s": WEDGE_S},
              chaos={"events": [{"action": "freeze_hb", "at_rx": 24}]})
    )
    runner = TopologyRunner(topo.build()).start()
    try:
        runner.wait_running(timeout_s=540)
        _run_until(runner,
                   lambda: runner.metrics("b")["sup_watchdog_trips"] >= 1
                   and runner.metrics("a")["tx"] >= N)
        assert runner.metrics("b")["sup_watchdog_trips"] >= 1
        assert runner.metrics("a")["tx"] == N
        # the trip also shows up through the monitor + prometheus paths
        from firedancer_tpu.disco.metrics import render_prometheus
        from firedancer_tpu.disco.monitor import snapshot
        snap = snapshot(runner.plan, runner.wksp)
        assert snap["b"]["metrics"]["sup_watchdog_trips"] >= 1
        text = render_prometheus(runner.plan, runner.wksp)
        assert 'name="sup_watchdog_trips"' in text
    finally:
        runner.halt(join_timeout_s=10)
        runner.close()


def test_circuit_breaker_halts_crash_loop_cleanly():
    """A tile that dies immediately on every boot exhausts its restart
    budget; the breaker opens, the topology is HALTED (not wedged, not
    respawning forever) and the failure surfaces as CircuitOpen. The
    chaos plan sets rearm=true so the crash survives respawn (default
    drills fire once per boot and the replacement comes up clean)."""
    topo = (
        Topology(f"sb{os.getpid()}", wksp_size=1 << 22)
        .link("a_b", depth=32, mtu=256)
        .tile("a", "synth", outs=["a_b"], count=1 << 20, unique=16,
              burst=8)
        .tile("b", "sink", ins=["a_b"],
              supervise={"policy": "restart", "backoff_s": 0.05,
                         "max_restarts": 1, "window_s": 60.0},
              chaos={"rearm": True,
                     "events": [{"action": "crash", "at_iter": 1}]})
    )
    runner = TopologyRunner(topo.build()).start()
    try:
        with pytest.raises(CircuitOpen, match="circuit breaker"):
            _run_until(runner, lambda: False, timeout_s=120)
        assert runner.metrics("b")["sup_restarts"] == 1
        time.sleep(0.2)
        for tn, p in runner.procs.items():
            assert not p.is_alive(), f"{tn} still running after halt"
    finally:
        runner.halt(join_timeout_s=10)
        runner.close()
