"""Sandbox hardening + tempo calibration tests
(ref: src/util/sandbox/fd_sandbox.h, src/tango/tempo/fd_tempo.c)."""
import os
import subprocess
import sys

import pytest


def test_sandbox_apply_in_subprocess():
    code = """
import resource
from firedancer_tpu.utils import sandbox
rep = sandbox.apply(max_files=128, max_mem_gb=0, close_high_fds=False)
assert rep["no_new_privs"], rep
assert rep["nofile"] == 128
assert resource.getrlimit(resource.RLIMIT_NOFILE) == (128, 128)
assert resource.getrlimit(resource.RLIMIT_CORE) == (0, 0)
nnp = [l for l in open("/proc/self/status") if l.startswith("NoNewPrivs")]
assert nnp and nnp[0].split()[1] == "1", nnp
print("SANDBOXED")
"""
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PALLAS_AXON_POOL_IPS="")
    r = subprocess.run([sys.executable, "-c", code], env=env,
                       capture_output=True, text=True, timeout=120,
                       cwd=os.path.dirname(os.path.dirname(
                           os.path.abspath(__file__))))
    assert "SANDBOXED" in r.stdout, r.stderr


@pytest.mark.slow
def test_sandboxed_tile_runs():
    from firedancer_tpu.disco import Topology, TopologyRunner
    os.environ.setdefault("FDTPU_JAX_PLATFORM", "cpu")
    topo = (
        Topology(f"sb{os.getpid()}", wksp_size=1 << 22)
        .link("a_b", depth=32, mtu=256)
        .tile("src", "synth", outs=["a_b"], count=8, sandbox=True)
        .tile("dst", "sink", ins=["a_b"], sandbox=True)
    )
    runner = TopologyRunner(topo.build()).start()
    try:
        runner.wait_running(timeout_s=120)
        import time
        deadline = time.time() + 30
        while time.time() < deadline:
            if runner.metrics("dst")["rx"] >= 8:
                break
            time.sleep(0.1)
        assert runner.metrics("dst")["rx"] >= 8
        for name, proc in runner.procs.items():
            nnp = [l for l in open(f"/proc/{proc.pid}/status")
                   if l.startswith("NoNewPrivs")]
            assert nnp[0].split()[1] == "1", (name, nnp)
    finally:
        runner.halt()
        runner.close()


def test_tempo_calibration_and_lazy_math():
    from firedancer_tpu.utils import tempo
    r = tempo.tick_per_ns(trials=5, window_s=0.002)
    # perf_counter_ns and time_ns both count ns: ratio ~1
    assert 0.5 < r < 2.0, r
    # lazy scales with the credit window
    assert tempo.lazy_default(64) < tempo.lazy_default(4096)
    assert tempo.lazy_default(1) >= 1_000
    # async_min: power of two, and event_cnt events fit within ~lazy
    for lazy, n in ((1_000_000, 7), (50_000, 3), (10_000, 1)):
        m = tempo.async_min(lazy, n)
        assert m & (m - 1) == 0
        assert m * n <= lazy
    with pytest.raises(ValueError):
        tempo.async_min(0, 1)


@pytest.mark.slow
def test_lazy_ns_pins_housekeeping_cadence():
    """A tile with lazy_ns set housekeeps at that cadence (observed
    through the poh tile: ticks are housekeeping-driven)."""
    import time

    from firedancer_tpu.disco import Topology, TopologyRunner
    os.environ.setdefault("FDTPU_JAX_PLATFORM", "cpu")
    topo = (
        Topology(f"tp{os.getpid()}", wksp_size=1 << 23)
        .link("drv_poh", depth=32, mtu=64)
        .link("poh_ent", depth=4096, mtu=256)
        .tile("drv", "synth", outs=["drv_poh"], count=0)
        .tile("poh", "poh", ins=["drv_poh"], outs=["poh_ent"],
              hashes_per_tick=4, ticks_per_slot=4, lazy_ns=2_000_000)
        .tile("snk", "sink", ins=["poh_ent"])
    )
    runner = TopologyRunner(topo.build()).start()
    try:
        runner.wait_running(timeout_s=120)
        t0 = time.time()
        ticks0 = runner.metrics("poh")["ticks"]
        time.sleep(2.0)
        rate = (runner.metrics("poh")["ticks"] - ticks0) \
            / (time.time() - t0)
        # 2ms lazy -> ~500 ticks/s; allow wide slack (single core box)
        assert 100 < rate < 1000, rate
    finally:
        runner.halt()
        runner.close()
