"""Gossip tests: CRDS LWW convergence, bloom pull anti-entropy, push
fan-out with prunes, and a randomized multi-node network simulation
converging to a consistent store (ref: src/flamenco/gossip/fd_gossip.h
protocol description; test tiers per test_gossip.c / test_bloom.c)."""
import numpy as np

from firedancer_tpu.gossip import (
    KIND_CONTACT_INFO, KIND_VOTE, Bloom, CrdsStore, CrdsValue, GossipNode,
)


def pk(i: int) -> bytes:
    return bytes([i]) * 32


# ---------------------------------------------------------------------------
# bloom
# ---------------------------------------------------------------------------

def test_bloom_membership_and_wire():
    rng = np.random.default_rng(1)
    f = Bloom.for_items(128, fp_rate=0.01, seed=42)
    keys = [rng.bytes(32) for _ in range(128)]
    for k in keys:
        f.insert(k)
    assert all(f.contains(k) for k in keys)          # no false negatives
    others = [rng.bytes(32) for _ in range(500)]
    fp = sum(f.contains(k) for k in others)
    assert fp < 25, f"false positive rate way off: {fp}/500"
    # CrdsFilter wire fields round-trip (the real pull-request form)
    fkeys, bits, nset = f.filter_fields()
    g = Bloom.from_filter(fkeys, bits, f.num_bits)
    assert all(g.contains(k) for k in keys)
    assert g.keys == f.keys and g.num_bits == f.num_bits
    assert nset == f.num_bits_set > 0


# ---------------------------------------------------------------------------
# crds
# ---------------------------------------------------------------------------

def test_crds_lww_upsert():
    s = CrdsStore()
    v1 = CrdsValue(pk(1), KIND_VOTE, 0, wallclock=100, data=b"a")
    v2 = CrdsValue(pk(1), KIND_VOTE, 0, wallclock=200, data=b"b")
    v0 = CrdsValue(pk(1), KIND_VOTE, 0, wallclock=50, data=b"z")
    assert s.upsert(v1)
    assert s.upsert(v2)                 # newer wins
    assert not s.upsert(v0)             # stale rejected
    assert not s.upsert(v2)             # tie keeps incumbent
    assert s.get(pk(1), KIND_VOTE).data == b"b"
    # distinct indices coexist
    assert s.upsert(CrdsValue(pk(1), KIND_VOTE, 1, 100, b"c"))
    assert len(s.values) == 2
    # the replaced value's hash left the bloom identity set
    assert v1.hash() not in s.hashes and v2.hash() in s.hashes


def test_crds_wire_roundtrip():
    from firedancer_tpu.flamenco import gossip_wire as gw
    ci = gw.ContactInfo(pubkey=pk(3), wallclock_ms=777,
                        sockets={gw.SOCKET_GOSSIP: ("10.0.0.3", 8000)})
    v = CrdsValue(pk(3), KIND_CONTACT_INFO, 0, 777, ci.encode(),
                  b"s" * 64)
    w, end = CrdsValue.from_wire(v.to_wire())
    assert w == v and end == len(v.to_wire())
    # the signable region is serialize(CrdsData): u32 tag + payload
    assert v.signable() == (11).to_bytes(4, "little") + ci.encode()


def test_crds_pull_missing():
    a, b = CrdsStore(), CrdsStore()
    vals = [CrdsValue(pk(i), KIND_VOTE, 0, 100 + i, bytes([i]))
            for i in range(1, 9)]
    for v in vals:
        a.upsert(v)
    for v in vals[:4]:
        b.upsert(v)
    missing = a.missing_for(b.bloom_of_contents(fp_rate=0.01))
    got = {v.key() for v in missing}
    assert got == {v.key() for v in vals[4:]}


def test_crds_purge():
    s = CrdsStore(max_age_ms=1000)
    s.upsert(CrdsValue(pk(1), KIND_VOTE, 0, 100, b"old"))
    s.upsert(CrdsValue(pk(2), KIND_VOTE, 0, 1900, b"new"))
    s.purge(now_ms=2000)
    assert s.get(pk(1), KIND_VOTE) is None
    assert s.get(pk(2), KIND_VOTE) is not None


# ---------------------------------------------------------------------------
# push / prune / network sim
# ---------------------------------------------------------------------------

def test_push_and_prune_flow():
    n = GossipNode(pk(1))
    # two relayers deliver the same values; the second accumulates
    # duplicates and gets pruned for that origin
    vals = [CrdsValue(pk(9), KIND_VOTE, i, 100 + i, bytes([i]))
            for i in range(4)]
    fresh = n.handle_push(vals, relayer=pk(2))
    assert len(fresh) == 4
    n.handle_push(vals, relayer=pk(3))
    due = n.prunes_due()
    assert pk(3) in due and due[pk(3)] == [pk(9)]
    assert not n.prunes_due()           # reported once


def test_network_convergence():
    """12 nodes, random sparse delivery of pushes + periodic bloom pulls:
    every node converges on every origin's LATEST value."""
    rng = np.random.default_rng(7)
    N = 12
    stakes = {pk(i): int(rng.integers(1, 100)) * 1000 for i in range(N)}
    nodes = [GossipNode(pk(i), stake_of=lambda p: stakes.get(p, 1),
                        active_set_size=4) for i in range(N)]
    # everyone learns everyone's contact info out of band (entrypoint
    # bootstrap abstracted away)
    for now, n in enumerate(nodes):
        n.tick(now_ms=1000)
        n.publish_contact_info((f"10.0.0.{n.pubkey[0]}", 8000))
    for n in nodes:
        for m in nodes:
            if n is not m:
                n.crds.upsert(m.crds.get(m.pubkey, KIND_CONTACT_INFO))

    # each node publishes 2 generations of a vote value
    for gen in range(2):
        for i, n in enumerate(nodes):
            n.tick(2000 + gen)
            n.make_value(KIND_VOTE, 0, b"gen%d-%d" % (gen, i))

    by_pk = {n.pubkey: n for n in nodes}
    # rounds of push gossip along each node's active set
    for _ in range(6):
        for n in nodes:
            for v in list(n.crds.values.values()):
                for tgt in n.push_targets_for(v):
                    if tgt == n.pubkey or tgt not in by_pk:
                        continue
                    if rng.random() < 0.3:
                        continue        # lossy network
                    by_pk[tgt].handle_push([v], relayer=n.pubkey)
    # anti-entropy: random pulls patch the holes
    for _ in range(4):
        for n in nodes:
            peer = by_pk[pk(int(rng.integers(0, N)))]
            if peer is n:
                continue
            resp = peer.handle_pull_request(n.make_pull_request(seed=3),
                                            limit=256)
            n.handle_pull_response(resp)

    for n in nodes:
        for i in range(N):
            v = n.crds.get(pk(i), KIND_VOTE)
            assert v is not None, f"node {n.pubkey[0]} missing origin {i}"
            assert v.data == b"gen1-%d" % i, "stale generation survived"


def test_push_respects_prunes():
    stakes = {pk(i): 1000 for i in range(6)}
    n = GossipNode(pk(1), stake_of=lambda p: stakes.get(p, 1),
                   active_set_size=5)
    n.tick(1000)
    for i in range(6):
        n.crds.upsert(CrdsValue(pk(i), KIND_CONTACT_INFO, 0, 1000,
                                b"addr"))
    v = CrdsValue(pk(9), KIND_VOTE, 0, 500, b"x")
    n.crds.upsert(v)
    tgts = n.push_targets_for(v)
    assert tgts
    n.handle_prune(tgts[0], [pk(9)])
    assert tgts[0] not in n.push_targets_for(v)
    # prune is per-origin: other origins still flow to that peer
    w = CrdsValue(pk(8), KIND_VOTE, 0, 500, b"y")
    n.crds.upsert(w)
    assert tgts[0] in n.push_targets_for(w)


def test_crds_value_rejects_wrong_width_fields():
    """Fixed-width wire fields: a 31-byte origin doesn't fail encode,
    it SHIFTS every later byte of the frame so peers decode garbage
    under a valid-looking tag. Construction is the only choke point."""
    import pytest
    with pytest.raises(ValueError, match="32-byte pubkey, got 31"):
        CrdsValue(bytes(31), KIND_VOTE, 0, 100, b"a")
    with pytest.raises(ValueError, match="64 bytes"):
        CrdsValue(pk(1), KIND_VOTE, 0, 100, b"a", signature=b"s" * 63)
    # the two legal shapes still construct
    CrdsValue(pk(1), KIND_VOTE, 0, 100, b"a")                 # unsigned
    CrdsValue(pk(1), KIND_VOTE, 0, 100, b"a", b"s" * 64)      # signed
