"""Snapshot archive format tests: AppendVec byte layout, streaming
tar, zstd restore with lattice verification, tamper detection
(ref: src/discof/restore/fd_snapin_tile.c:14-17 tar+AppendVec parse,
snapla/snapls lattice verify fan-out)."""
import io
import struct
import tarfile

import pytest

from firedancer_tpu.flamenco.snapshot import (
    SnapshotRestorer, TarStream, parse_append_vec, restore_snapshot,
    write_append_vec, write_snapshot_archive,
)
from firedancer_tpu.funk.funk import Funk
from firedancer_tpu.svm.accdb import Account


def k(n):
    return bytes([n]) * 32


def test_append_vec_byte_layout():
    """The exact Agave entry layout: 48B StoredMeta + 56B AccountMeta
    + 32B stored hash (vestigial zeros) + data padded to 8 — the
    136-byte STORE_META_OVERHEAD."""
    a = Account(lamports=7, data=b"hello", owner=k(9),
                executable=True, rent_epoch=3)
    b = write_append_vec([(k(1), a)])
    # StoredMeta: write_version 0, data_len 5, pubkey
    assert b[0:8] == bytes(8)
    assert struct.unpack_from("<Q", b, 8)[0] == 5
    assert b[16:48] == k(1)
    # AccountMeta: lamports, rent_epoch, owner, executable + 7 pad
    assert struct.unpack_from("<Q", b, 48)[0] == 7
    assert struct.unpack_from("<Q", b, 56)[0] == 3
    assert b[64:96] == k(9)
    assert b[96] == 1 and b[97:104] == bytes(7)
    assert b[104:136] == bytes(32)               # stored hash field
    assert b[136:141] == b"hello"
    assert len(b) == 136 + 5 + 3                 # padded to 8
    [(pk, back)] = parse_append_vec(b)
    assert pk == k(1)
    assert (back.lamports, back.data, back.owner, back.executable,
            back.rent_epoch) == (7, b"hello", k(9), True, 3)


def test_append_vec_bounds_checked():
    a = Account(lamports=1, data=b"x" * 32)
    b = bytearray(write_append_vec([(k(1), a)]))
    struct.pack_into("<Q", b, 8, 1 << 40)        # hostile data_len
    with pytest.raises(ValueError):
        parse_append_vec(bytes(b))


def test_tar_stream_incremental():
    buf = io.BytesIO()
    with tarfile.open(fileobj=buf, mode="w",
                      format=tarfile.USTAR_FORMAT) as tf:
        for name, data in (("a", b"A" * 700), ("dir/b", b"B" * 3)):
            ti = tarfile.TarInfo(name)
            ti.size = len(data)
            tf.addfile(ti, io.BytesIO(data))
    raw = buf.getvalue()
    ts = TarStream()
    got = []
    for i in range(0, len(raw), 97):             # awkward chunking
        got.extend(ts.feed(raw[i:i + 97]))
    assert got == [("a", b"A" * 700), ("dir/b", b"B" * 3)]
    assert ts.done


def _funk_with_accounts(n=300):
    funk = Funk()
    for i in range(n):
        funk.rec_write(None, bytes([i % 256, i // 256]) + bytes(30),
                       Account(lamports=i + 1,
                               data=bytes([i & 0xFF]) * (i % 50),
                               owner=k(7), rent_epoch=i % 5))
    return funk


def test_archive_roundtrip_with_lattice_verify(tmp_path):
    pytest.importorskip("zstandard")
    path = str(tmp_path / "snap.tar.zst")
    funk = _funk_with_accounts()
    write_snapshot_archive(path, 42, funk, accounts_per_vec=64)
    funk2 = Funk()
    slot, ok = restore_snapshot(path, funk2)
    assert slot == 42 and ok
    assert funk2.root_items().keys() == funk.root_items().keys()
    for key, a in funk.root_items().items():
        b = funk2.rec_query(None, key)
        assert (a.lamports, a.data, a.owner, a.rent_epoch) == \
            (b.lamports, b.data, b.owner, b.rent_epoch)


def test_tampered_archive_fails_lattice_verify(tmp_path):
    pytest.importorskip("zstandard")
    import zstandard
    path = str(tmp_path / "snap.tar.zst")
    funk = _funk_with_accounts(50)
    write_snapshot_archive(path, 7, funk)
    raw = zstandard.ZstdDecompressor().decompress(
        open(path, "rb").read(), max_output_size=1 << 24)
    # locate the accounts member's tar HEADER (512-aligned, name at
    # block start — a plain find() would hit the manifest's name list)
    idx = next(off for off in range(0, len(raw), 512)
               if raw[off:off + 13] == b"accounts/7.0\x00")
    tampered = bytearray(raw)
    # first entry's AccountMeta.lamports sits 48 bytes into the data
    tampered[idx + 512 + 48] ^= 1
    open(path, "wb").write(
        zstandard.ZstdCompressor().compress(bytes(tampered)))
    funk2 = Funk()
    slot, ok = restore_snapshot(path, funk2)
    assert slot == 7 and not ok                  # lattice catches it


def test_streaming_restorer_chunked(tmp_path):
    pytest.importorskip("zstandard")
    path = str(tmp_path / "snap.tar.zst")
    funk = _funk_with_accounts(120)
    write_snapshot_archive(path, 9, funk, accounts_per_vec=32)
    funk2 = Funk()
    r = SnapshotRestorer(funk2)
    blob = open(path, "rb").read()
    for i in range(0, len(blob), 333):           # tiny odd chunks
        r.feed(blob[i:i + 333])
    assert r.finish()
    assert r.accounts == 120 and r.slot == 9


def test_missing_vec_fails(tmp_path):
    pytest.importorskip("zstandard")
    import zstandard
    path = str(tmp_path / "snap.tar.zst")
    funk = _funk_with_accounts(80)
    write_snapshot_archive(path, 3, funk, accounts_per_vec=32)
    raw = zstandard.ZstdDecompressor().decompress(
        open(path, "rb").read(), max_output_size=1 << 24)
    # rebuild the tar WITHOUT the last accounts member
    ts = TarStream()
    members = ts.feed(raw)
    keep = [m for m in members if m[0] != "accounts/3.2"]
    assert len(keep) == len(members) - 1
    buf = io.BytesIO()
    with tarfile.open(fileobj=buf, mode="w",
                      format=tarfile.USTAR_FORMAT) as tf:
        for name, data in keep:
            ti = tarfile.TarInfo(name)
            ti.size = len(data)
            tf.addfile(ti, io.BytesIO(data))
    open(path, "wb").write(
        zstandard.ZstdCompressor().compress(buf.getvalue()))
    funk2 = Funk()
    slot, ok = restore_snapshot(path, funk2)
    assert not ok


@pytest.mark.slow
def test_snapld_snapdc_snapin_pipeline(tmp_path):
    pytest.importorskip("zstandard")
    """The full restore tile chain over rings: file -> snapld ->
    snapdc (zstd) -> snapin (tar+AppendVec), lattice verified."""
    import os
    import time

    from firedancer_tpu.disco import Topology, TopologyRunner
    os.environ.setdefault("FDTPU_JAX_PLATFORM", "cpu")
    path = str(tmp_path / "snap.tar.zst")
    funk = _funk_with_accounts(200)
    write_snapshot_archive(path, 11, funk, accounts_per_vec=64)
    topo = (
        Topology(f"sn{os.getpid()}", wksp_size=1 << 24)
        .link("ld_dc", depth=256, mtu=4096)
        .link("dc_in", depth=256, mtu=4096)
        .tile("snapld", "snapld", outs=["ld_dc"], path=path, chunk=3000)
        .tile("snapdc", "snapdc", ins=["ld_dc"], outs=["dc_in"])
        .tile("snapin", "snapin", ins=["dc_in"], format="archive")
    )
    runner = TopologyRunner(topo.build()).start()
    try:
        runner.wait_running(timeout_s=120)
        deadline = time.time() + 120
        while time.time() < deadline:
            m = runner.metrics("snapin")
            if m["restored"]:
                break
            time.sleep(0.2)
        m = runner.metrics("snapin")
        assert m["restored"] == 1
        assert m["accounts"] == 200
        assert m["slot"] == 11
        assert m["lattice_ok"] == 1
        assert m["stream_err"] == 0
    finally:
        runner.halt()
        runner.close()
