"""WebSocket pub-sub tests: RFC 6455 handshake/frames and the Solana
subscription envelopes (ref: src/discof/rpc/ subscription API over
src/waltz/http upgrade path)."""
import base64
import hashlib
import json
import os
import socket
import struct
import time

import pytest

from firedancer_tpu.rpc.ws import WsServer
from firedancer_tpu.svm.accdb import Account
from firedancer_tpu.utils.base58 import b58_encode_32


class WsClient:
    """Tiny RFC 6455 client: masked frames, blocking reads."""

    def __init__(self, port):
        self.sock = socket.create_connection(("127.0.0.1", port),
                                             timeout=30)
        key = base64.b64encode(os.urandom(16)).decode()
        self.sock.sendall((
            f"GET / HTTP/1.1\r\nHost: x\r\nUpgrade: websocket\r\n"
            f"Connection: Upgrade\r\nSec-WebSocket-Key: {key}\r\n"
            f"Sec-WebSocket-Version: 13\r\n\r\n").encode())
        resp = b""
        while b"\r\n\r\n" not in resp:
            resp += self.sock.recv(4096)
        assert b"101" in resp.split(b"\r\n")[0]
        want = base64.b64encode(hashlib.sha1(
            key.encode()
            + b"258EAFA5-E914-47DA-95CA-C5AB0DC85B11").digest())
        assert want in resp                      # accept key verified

    def send_json(self, obj):
        payload = json.dumps(obj).encode()
        mask = os.urandom(4)
        masked = bytes(b ^ mask[i & 3] for i, b in enumerate(payload))
        hdr = bytes([0x81])
        n = len(payload)
        assert n < 126
        self.sock.sendall(hdr + bytes([0x80 | n]) + mask + masked)

    def recv_json(self):
        b0 = self._exact(2)
        n = b0[1] & 0x7F
        if n == 126:
            n, = struct.unpack(">H", self._exact(2))
        return json.loads(self._exact(n))

    def _exact(self, n):
        out = b""
        while len(out) < n:
            c = self.sock.recv(n - len(out))
            assert c
            out += c
        return out

    def close(self):
        self.sock.close()


def test_ws_slot_and_account_subscriptions():
    srv = WsServer()
    c = WsClient(srv.port)
    c.send_json({"jsonrpc": "2.0", "id": 1, "method": "slotSubscribe"})
    sub_slot = c.recv_json()["result"]
    pk = b"\x11" * 32
    c.send_json({"jsonrpc": "2.0", "id": 2,
                 "method": "accountSubscribe",
                 "params": [b58_encode_32(pk)]})
    sub_acct = c.recv_json()["result"]
    assert sub_slot != sub_acct
    time.sleep(0.05)

    srv.publish_slot(77)
    note = c.recv_json()
    assert note["method"] == "slotNotification"
    assert note["params"] == {"subscription": sub_slot,
                              "result": {"slot": 77}}

    srv.publish_account(pk, Account(lamports=555, data=b"ab",
                                    owner=b"\x07" * 32), slot=77)
    note = c.recv_json()
    assert note["method"] == "accountNotification"
    v = note["params"]["result"]["value"]
    assert v["lamports"] == 555
    assert v["data"] == [base64.b64encode(b"ab").decode(), "base64"]
    # a different account does NOT notify; unsubscribe stops slot notes
    srv.publish_account(b"\x22" * 32, Account(lamports=1), slot=78)
    c.send_json({"jsonrpc": "2.0", "id": 3,
                 "method": "slotUnsubscribe", "params": [sub_slot]})
    assert c.recv_json()["result"] is True
    time.sleep(0.05)
    srv.publish_slot(78)
    # only traffic left should be nothing: probe with a fresh request
    c.send_json({"jsonrpc": "2.0", "id": 4, "method": "nosuch"})
    assert "error" in c.recv_json()
    c.close()
    srv.close()


def test_ws_ping_pong_and_bad_method():
    srv = WsServer()
    c = WsClient(srv.port)
    # ping -> pong echo
    mask = os.urandom(4)
    body = bytes(b ^ mask[i & 3] for i, b in enumerate(b"hi"))
    c.sock.sendall(bytes([0x89, 0x82]) + mask + body)
    hdr = c._exact(2)
    assert hdr[0] & 0x0F == 0xA
    assert c._exact(hdr[1] & 0x7F) == b"hi"
    c.close()
    srv.close()


@pytest.mark.slow
def test_bank_tile_ws_notifications():
    """The leader loop's bank tile pushes slot + account notifications
    to a live websocket subscriber."""
    from firedancer_tpu.disco import Topology, TopologyRunner
    from firedancer_tpu.tiles.synth import make_signed_txns, synth_signer_seed
    from firedancer_tpu.utils.ed25519_ref import keypair
    from firedancer_tpu.protocol.txn import parse_txn
    os.environ.setdefault("FDTPU_JAX_PLATFORM", "cpu")
    N = 8
    genesis = {keypair(synth_signer_seed(i))[-1].hex(): 1 << 44
               for i in range(16)}
    topo = (
        Topology(f"ws{os.getpid()}", wksp_size=1 << 25)
        .link("synth_verify", depth=128, mtu=1280)
        .link("verify_pack", depth=128, mtu=1280)
        .link("pack_bank0", depth=32, mtu=1 << 14)
        .link("bank0_done", depth=32, mtu=64)
        .tcache("verify_tc", depth=4096)
        .tile("synth", "synth", outs=["synth_verify"], count=N,
              unique=N, seed=6)
        .tile("verify", "verify", ins=["synth_verify"],
              outs=["verify_pack"], batch=16, tcache="verify_tc")
        .tile("pack", "pack", ins=["verify_pack", "bank0_done"],
              outs=["pack_bank0"], txn_in="verify_pack",
              bank_links=["pack_bank0"], done_links=["bank0_done"],
              slot_ms=200.0, max_txn_per_microblock=4)
        .tile("bank0", "bank", ins=["pack_bank0"],
              outs=["bank0_done"], exec="svm", genesis=genesis,
              ws_port=0)
    )
    runner = TopologyRunner(topo.build()).start()
    try:
        runner.wait_running(timeout_s=540)
        deadline = time.time() + 60
        while time.time() < deadline \
                and runner.metrics("bank0")["ws_port"] == 0:
            time.sleep(0.1)
        port = int(runner.metrics("bank0")["ws_port"])
        c = WsClient(port)
        # subscribe to a synth destination account
        txns = make_signed_txns(N, seed=6)
        t0 = parse_txn(txns[0])
        dst = t0.account_keys(txns[0])[1]
        c.send_json({"jsonrpc": "2.0", "id": 1,
                     "method": "accountSubscribe",
                     "params": [b58_encode_32(dst)]})
        assert isinstance(c.recv_json()["result"], int)
        c.send_json({"jsonrpc": "2.0", "id": 2,
                     "method": "slotSubscribe"})
        assert isinstance(c.recv_json()["result"], int)
        got_acct = got_slot = False
        deadline = time.time() + 120
        c.sock.settimeout(120)
        while time.time() < deadline and not (got_acct and got_slot):
            note = c.recv_json()
            if note.get("method") == "accountNotification":
                got_acct = True
                assert note["params"]["result"]["value"]["lamports"] > 0
            elif note.get("method") == "slotNotification":
                got_slot = True
        assert got_acct and got_slot
        c.close()
    finally:
        runner.halt()
        runner.close()
