"""Aux tiles: pcap codec + replay tile, ipecho service, cswtch
sampler (ref: src/disco/pcap/fd_pcap_replay_tile.c,
src/discof/ipecho/, src/disco/cswtch/fd_cswtch_tile.c)."""
import io
import os
import struct
import time

import pytest

from firedancer_tpu.utils.pcap import (LINKTYPE_USER0, read_pcap,
                                       write_pcap)


def test_pcap_roundtrip_and_endian_tolerance():
    pkts = [(1_000_000 + i * 137, os.urandom(20 + i)) for i in range(9)]
    buf = io.BytesIO()
    write_pcap(buf, pkts)
    buf.seek(0)
    assert list(read_pcap(buf)) == pkts
    # torn tail: truncated final packet is dropped, not an error
    raw = buf.getvalue()
    buf2 = io.BytesIO(raw[:-5])
    assert list(read_pcap(buf2)) == pkts[:-1]
    with pytest.raises(ValueError):
        list(read_pcap(io.BytesIO(b"\x00" * 40)))


def test_ipecho_service_roundtrip():
    from firedancer_tpu.disco.tiles import IpechoAdapter, ipecho_query

    class Ctx:
        plan = {"topology": "t", "tiles": {}}
        tile_name = "ipecho"
        in_rings = {}
        out_rings = {}
        out_fseqs = {}

    a = IpechoAdapter(Ctx(), {"shred_version": 5122})
    try:
        sv, ip, port = ipecho_query(("127.0.0.1", a.port))
        assert sv == 5122
        assert ip == "127.0.0.1" and port > 0
        assert a.queries == 1
    finally:
        a.on_halt()


def test_cswtch_samples_own_process(tmp_path):
    from firedancer_tpu.disco.tiles import CswtchAdapter

    class Ctx:
        plan = {"topology": f"cs{os.getpid()}", "tiles": {"me": {}}}
        tile_name = "cswtch"
        in_rings = {}
        out_rings = {}
        out_fseqs = {}

    topo = Ctx.plan["topology"]
    with open(f"/dev/shm/fdtpu_{topo}.pid.me", "w") as f:
        f.write(str(os.getpid()))
    try:
        a = CswtchAdapter(Ctx(), {})
        a.housekeeping()
        m = a.metrics_items()
        assert m["tiles_sampled"] == 1
        assert m["vol"] > 0              # this process has switched
        assert m["max_invol"] == m["invol"]
    finally:
        os.unlink(f"/dev/shm/fdtpu_{topo}.pid.me")


def test_pcap_tile_replays_into_topology(tmp_path):
    """pcap tile -> sink across real processes; payloads byte-exact
    and in order."""
    from firedancer_tpu.disco import Topology, TopologyRunner

    pkts = [(i * 1000, bytes([i]) * (60 + i)) for i in range(1, 33)]
    path = str(tmp_path / "cap.pcap")
    with open(path, "wb") as f:
        write_pcap(f, pkts)

    topo = (
        Topology(f"pc{os.getpid()}", wksp_size=1 << 22)
        .link("replayed", depth=64, mtu=256)
        .tile("pcap", "pcap", outs=["replayed"], path=path, loop=2)
        .tile("sink", "sink", ins=["replayed"])
    )
    runner = TopologyRunner(topo.build()).start()
    try:
        runner.wait_running(timeout_s=60)
        # sink rx lands a housekeeping flush BEFORE the pcap tile's
        # own tx/done counters do — poll both sides of the link to
        # the same deadline, assert once, after
        deadline = time.time() + 30
        while time.time() < deadline:
            p = runner.metrics("pcap")
            if (runner.metrics("sink")["rx"] >= 2 * len(pkts)
                    and p["tx"] >= 2 * len(pkts) and p["done"]):
                break
            time.sleep(0.05)
        assert runner.metrics("sink")["rx"] == 2 * len(pkts)
        p = runner.metrics("pcap")
        assert p["tx"] == 2 * len(pkts) and p["done"] == 1
    finally:
        runner.halt()
        runner.close()


def test_pcap_tile_empty_capture_is_done_not_crash(tmp_path):
    from firedancer_tpu.disco.tiles import PcapAdapter

    path = str(tmp_path / "empty.pcap")
    with open(path, "wb") as f:
        write_pcap(f, [])

    class Ring:
        def credits(self, fseqs):
            return 1

        def publish(self, *a, **kw):
            raise AssertionError("nothing to publish")

    class Ctx:
        plan = {"topology": "t", "tiles": {},
                "links": {"out": {"mtu": 256, "depth": 8}}}
        tile_name = "pcap"
        in_rings = {}
        out_rings = {"out": Ring()}
        out_fseqs = {"out": []}

    a = PcapAdapter(Ctx(), {"path": path, "loop": 3})
    for _ in range(5):
        assert a.poll_once() == 0
    assert a.metrics_items()["done"] == 1


def test_cswtch_ignores_recycled_pid():
    from firedancer_tpu.disco.tiles import CswtchAdapter

    class Ctx:
        plan = {"topology": f"cr{os.getpid()}", "tiles": {"ghost": {}}}
        tile_name = "cswtch"
        in_rings = {}
        out_rings = {}
        out_fseqs = {}

    topo = Ctx.plan["topology"]
    # stale pidfile: right pid, WRONG starttime
    with open(f"/dev/shm/fdtpu_{topo}.pid.ghost", "w") as f:
        f.write(f"{os.getpid()} 12345")
    try:
        a = CswtchAdapter(Ctx(), {})
        a.housekeeping()
        assert a.metrics_items()["tiles_sampled"] == 0
    finally:
        os.unlink(f"/dev/shm/fdtpu_{topo}.pid.ghost")


def test_gui_tile_serves_dashboard_and_summary():
    """gui tile in a live topology: the page serves, summary.json
    reflects real tile metrics, TPS turns nonzero under load."""
    import json as _json
    import urllib.request

    from firedancer_tpu.disco import Topology, TopologyRunner

    pkts = [(i * 10, bytes([i % 250 + 1]) * 80) for i in range(400)]
    import tempfile
    cap = tempfile.NamedTemporaryFile(suffix=".pcap", delete=False)
    with open(cap.name, "wb") as f:
        write_pcap(f, pkts)

    topo = (
        Topology(f"gt{os.getpid()}", wksp_size=1 << 22)
        .link("feed", depth=256, mtu=256)
        .tile("pcap", "pcap", outs=["feed"], path=cap.name, loop=50)
        .tile("sink", "sink", ins=["feed"])
        .tile("gui", "gui", port=0)
    )
    runner = TopologyRunner(topo.build()).start()
    try:
        runner.wait_running(timeout_s=60)
        deadline = time.time() + 30
        port = 0
        while time.time() < deadline:
            port = int(runner.metrics("gui").get("port", 0))
            if port:
                break
            time.sleep(0.05)
        assert port
        page = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/", timeout=10).read()
        assert b"firedancer-tpu" in page
        deadline = time.time() + 30
        tps = 0.0
        while time.time() < deadline:
            s = _json.loads(urllib.request.urlopen(
                f"http://127.0.0.1:{port}/summary.json",
                timeout=10).read())
            assert set(s["tiles"]) == {"pcap", "sink", "gui"}
            if s["tps"] > 0 and s["tiles"]["sink"]["metrics"]["rx"] > 0:
                tps = s["tps"]
                break
            time.sleep(0.3)
        assert tps > 0
    finally:
        runner.halt()
        runner.close()
        os.unlink(cap.name)


def test_plugin_tile_streams_events_over_unix_socket(tmp_path):
    """plugin tile: frag stream -> NDJSON events to an external unix-
    socket client (ref: src/disco/plugin/fd_plugin_tile.c role)."""
    import json as _json
    import socket as _s

    from firedancer_tpu.disco import Topology, TopologyRunner

    # 300 ms between packets: realtime pacing holds the stream open
    # long enough for the client to attach (events emitted before any
    # client connects are dropped by design)
    pkts = [(i * 300_000, bytes([i]) * 50) for i in range(1, 17)]
    cap = str(tmp_path / "c.pcap")
    with open(cap, "wb") as f:
        write_pcap(f, pkts)
    sock_path = str(tmp_path / "plugin.sock")
    topo = (
        Topology(f"pl{os.getpid()}", wksp_size=1 << 22)
        .link("feed", depth=64, mtu=256)
        .tile("pcap", "pcap", outs=["feed"], path=cap, loop=3,
              realtime=True)                     # paced: client attaches
        .tile("plugin", "plugin", ins=[("feed", False)],
              sock_path=sock_path)
    )
    runner = TopologyRunner(topo.build()).start()
    try:
        runner.wait_running(timeout_s=60)
        deadline = time.time() + 10
        cli = None
        while time.time() < deadline and cli is None:
            try:
                cli = _s.socket(_s.AF_UNIX, _s.SOCK_STREAM)
                cli.connect(sock_path)
            except OSError:
                cli = None
                time.sleep(0.05)
        assert cli is not None
        cli.settimeout(20)
        buf = b""
        events = []
        while len(events) < 10:
            chunk = cli.recv(4096)
            if not chunk:
                break
            buf += chunk
            while b"\n" in buf:
                line, buf = buf.split(b"\n", 1)
                events.append(_json.loads(line))
        assert len(events) >= 10
        assert events[0]["link"] == "feed"
        assert all(e["sz"] == 50 for e in events[:10])
        # payload prefix round-trips
        tag = int(events[0]["data"][:2], 16)
        assert events[0]["data"] == bytes([tag]).hex() * 50
        cli.close()
    finally:
        runner.halt()
        runner.close()
