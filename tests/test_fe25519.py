"""fe25519 vs python bigint oracle (ref test model: src/ballet/ed25519/test_ed25519.c)."""
import secrets

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from firedancer_tpu.ops import fe25519 as fe

P = fe.P


def rand_ints(n, bound=P):
    return [secrets.randbelow(bound) for _ in range(n)]


def to_limbs(xs):
    return jnp.asarray(np.stack([fe._int_to_limbs(x) for x in xs]))


def from_limbs(arr):
    arr = np.asarray(arr)
    return [fe.limbs_to_int(arr[i]) for i in range(arr.shape[0])]


def check_loose(arr):
    # loose-normalized invariant: non-negative limbs < 2^13 + 608
    # (see fe25519 module docstring bound analysis)
    arr = np.asarray(arr)
    assert arr.min() >= 0 and arr.max() < 2 ** 13 + 608


@pytest.mark.parametrize("op,pyop", [
    (fe.add, lambda a, b: (a + b) % P),
    (fe.sub, lambda a, b: (a - b) % P),
    (fe.mul, lambda a, b: (a * b) % P),
])
def test_binary_ops(op, pyop):
    n = 64
    a_int = rand_ints(n) + [0, P - 1, P, 2 ** 255 - 1, 1, 0, P - 1, 2 ** 255 - 1]
    b_int = rand_ints(n) + [0, P - 1, P, 2 ** 255 - 1, 0, 2 ** 255 - 1, 1, 1]
    a, b = to_limbs(a_int), to_limbs(b_int)
    out = jax.jit(op)(a, b)
    check_loose(out)
    got = from_limbs(out)
    for g, x, y in zip(got, a_int, b_int):
        assert g % P == pyop(x, y) % P


def test_chained_sub_stays_in_bounds():
    # worst case: repeated subtraction of large from small
    a = to_limbs([1, 0, P - 1])
    b = to_limbs([P - 1, 2 ** 255 - 1, 1])
    x = a
    expect = [1, 0, P - 1]
    for _ in range(5):
        x = fe.sub(x, b)
        check_loose(x)
        expect = [(e - y) % P for e, y in zip(expect, [P - 1, 2 ** 255 - 1, 1])]
    assert [g % P for g in from_limbs(x)] == expect


def test_sq_neg_invert():
    xs = rand_ints(16) + [1, 2, P - 1]
    a = to_limbs(xs)
    assert [g % P for g in from_limbs(fe.sq(a))] == [x * x % P for x in xs]
    assert [g % P for g in from_limbs(fe.neg(a))] == [(-x) % P for x in xs]
    inv = fe.invert(a)
    assert [g % P for g in from_limbs(inv)] == [pow(x, P - 2, P) for x in xs]


def test_canonical_and_eq():
    xs = [0, 1, P - 1, P, P + 1, 2 * P - 1, 2 ** 255 - 1]
    a = to_limbs(xs)
    can = fe.canonical(a)
    assert from_limbs(can) == [x % P for x in xs]
    assert list(np.asarray(fe.is_zero(to_limbs([0, P, 1, 2 * P])))) == [True, True, False, True]
    assert bool(fe.eq(to_limbs([P + 3])[0], to_limbs([3])[0]))


def test_bytes_roundtrip():
    xs = rand_ints(8) + [0, 1, P - 1]
    a = to_limbs(xs)
    b = fe.tobytes(a)
    assert b.shape == (len(xs), 32)
    for i, x in enumerate(xs):
        assert bytes(np.asarray(b[i]).tobytes()) == (x % P).to_bytes(32, "little")
    rt = fe.frombytes(b)
    assert from_limbs(rt) == [x % P for x in xs]
    # bit 255 ignored on input
    hi = np.asarray(b).copy()
    hi[:, 31] |= 0x80
    assert from_limbs(fe.frombytes(jnp.asarray(hi))) == [x % P for x in xs]


def test_constants():
    assert fe.limbs_to_int(fe.D_LIMBS) == fe.d
    assert fe.limbs_to_int(fe.SQRT_M1_LIMBS) == fe.SQRT_M1
    assert pow(fe.SQRT_M1, 2, P) == P - 1
