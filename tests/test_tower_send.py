"""Tower + send tile integration: block/vote frames -> fork choice ->
tower vote -> keyguard-signed vote txn over UDP
(ref: src/discof/tower/fd_tower_tile.c, src/discof/send/,
src/disco/keyguard/ role SEND)."""
import pytest

pytestmark = pytest.mark.slow
import os
import socket
import struct
import time

from firedancer_tpu.disco import Topology, TopologyRunner
from firedancer_tpu.protocol.txn import parse_txn
from firedancer_tpu.runtime import Ring
from firedancer_tpu.svm.vote import VOTE_PROGRAM_ID
from firedancer_tpu.tiles.tower import TowerCore, pack_block, pack_vote
from firedancer_tpu.utils.ed25519_ref import keypair, verify

SEED = bytes(range(32))
_, _, IDENTITY = keypair(SEED)
VOTE_ACCT = b"\x42" * 32


def bid(n):
    return n.to_bytes(32, "little")


# ---------------------------------------------------------------------------
# core logic
# ---------------------------------------------------------------------------

def test_tower_core_votes_follow_heaviest_fork():
    c = TowerCore(total_stake=200)
    c.handle(pack_block(1, 0, bid(1), bid(0)))
    c.handle(pack_block(2, 1, bid(2), bid(1)))
    c.handle(pack_vote(b"v1" * 16, 60, bid(2)))
    slot, blk = c.decide()
    assert (slot, blk) == (2, bid(2))
    # rival fork wins fork choice (65 > 60) past our lockout (slot 5 >
    # exp 4) but holds only 32.5% < 38%: the switch check refuses
    c.handle(pack_block(5, 1, bid(5), bid(1)))
    c.handle(pack_vote(b"v2" * 16, 65, bid(5)))
    assert c.decide() is None
    assert c.metrics["switch_skips"] == 1
    # more stake lands on the rival (85/200 >= 38%): switch allowed
    c.handle(pack_vote(b"v3" * 16, 20, bid(5)))
    slot, blk = c.decide()
    assert (slot, blk) == (5, bid(5))


def test_tower_core_roots_and_publishes():
    c = TowerCore(total_stake=100)
    c.tower.max = 4                       # small tower for the test
    prev = bid(0)
    for s in range(1, 8):
        c.handle(pack_block(s, s - 1, bid(s), prev))
        c.handle(pack_vote(b"v1" * 16, 80, bid(s)))
        c.decide()
        prev = bid(s)
    assert c.metrics["roots"] >= 1
    assert c.metrics["root_slot"] >= 1
    assert c.ghost.root == c.vote_blocks[c.metrics["root_slot"]]


# ---------------------------------------------------------------------------
# tiles end-to-end
# ---------------------------------------------------------------------------

def test_tower_send_sign_pipeline():
    os.environ.setdefault("FDTPU_JAX_PLATFORM", "cpu")
    rx = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    rx.bind(("127.0.0.1", 0))
    rx.settimeout(60)
    dest = f"127.0.0.1:{rx.getsockname()[1]}"

    topo = (
        Topology(f"tw{os.getpid()}", wksp_size=1 << 23)
        .link("replay_tower", depth=64, mtu=128)
        .link("tower_votes", depth=32, mtu=512)
        .link("send_req", depth=16, mtu=1280)
        .link("sign_resp", depth=16, mtu=128)
        .tile("driver", "synth", outs=["replay_tower"], count=0)
        .tile("tower", "tower", ins=[("replay_tower", False)],
              outs=["tower_votes"], total_stake=100)
        .tile("send", "send", ins=["tower_votes", ("sign_resp", False)],
              outs=["send_req"],
              identity_hex=IDENTITY.hex(),
              vote_account_hex=VOTE_ACCT.hex(), dest=dest,
              req="send_req", resp="sign_resp")
        .tile("sign", "sign", ins=[("send_req", False)],
              outs=["sign_resp"], seed=SEED.hex(),
              clients=[{"role": "send", "req": "send_req",
                        "resp": "sign_resp"}])
    )
    plan = topo.build()
    runner = TopologyRunner(plan).start(
        tiles=["tower", "send", "sign"])
    try:
        runner.wait_running(timeout_s=120)
        li = plan["links"]["replay_tower"]
        feed = Ring(runner.wksp, li["ring_off"], li["depth"],
                    li["arena_off"], li["mtu"])
        feed.publish(pack_block(5, 4, bid(5), bid(4)), sig=0)
        feed.publish(pack_vote(b"w1" * 16, 70, bid(5)), sig=1)

        data, _ = rx.recvfrom(2048)        # the signed vote txn
        t = parse_txn(data)
        keys = t.account_keys(data)
        assert keys[0] == IDENTITY
        assert VOTE_PROGRAM_ID in keys
        # signature verifies under the SIGN TILE's identity over the
        # message — the send tile never held the key
        assert verify(t.signatures(data)[0], IDENTITY, t.message(data))
        ix = t.instrs[0]
        ix_data = data[ix.data_off:ix.data_off + ix.data_sz]
        # real VoteInstruction::TowerSync (disc 14): u64 lockouts len,
        # then (u64 slot, u32 conf) entries
        (disc, cnt) = struct.unpack_from("<IQ", ix_data, 0)
        (slot, conf) = struct.unpack_from("<QI", ix_data, 12)
        assert disc == 14 and cnt >= 1 and slot == 5 and conf >= 1
        deadline = time.time() + 30
        while time.time() < deadline:
            if runner.metrics("send")["sent"] >= 1:
                break
            time.sleep(0.05)
        assert runner.metrics("send")["sign_fail"] == 0
        assert runner.metrics("tower")["votes_out"] >= 1
    finally:
        runner.halt()
        runner.close()
        rx.close()


def test_tower_threshold_check_blocks_unconfirmed_deep_vote():
    # ADVICE r3: per-voter towers feed the depth-8 threshold check.
    # One lone voter (10% stake) confirms our fork; after 8 of our own
    # votes the depth-8 vote lacks 2/3 support and voting must pause.
    c = TowerCore(total_stake=100)
    prev = bid(0)
    voted = 0
    for s in range(1, 20):
        c.handle(pack_block(s, s - 1, bid(s), prev))
        c.handle(pack_vote(b"w1" * 16, 10, bid(s)))
        if c.decide() is not None:
            voted += 1
        prev = bid(s)
    assert c.metrics["threshold_skips"] > 0
    # votes pause whenever the tower is 8 deep (expiry can re-open it,
    # so the count is < every-slot but not zero)
    assert voted < 19


def test_tower_threshold_check_passes_with_supermajority():
    c = TowerCore(total_stake=100)
    prev = bid(0)
    voted = 0
    for s in range(1, 20):
        c.handle(pack_block(s, s - 1, bid(s), prev))
        for v in range(7):               # 70% stake confirms each slot
            c.handle(pack_vote(bytes([v + 1]) * 32, 10, bid(s)))
        if c.decide() is not None:
            voted += 1
        prev = bid(s)
    assert voted == 19
    assert c.metrics["threshold_skips"] == 0
