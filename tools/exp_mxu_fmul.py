"""MXU field-multiply experiment — PERF.md item 4, the 1M/s unlock.

Question: can the 255-bit field multiply's digit convolution ride the
MXU (systolic array) instead of the VPU? The VPU floor measured in r3
is ~0.65 ns/fmul/lane (tools/exp_vpu.py, ops/pallas_ed.py roll-mac of
20x20 radix-2^13 limbs). The MXU multiplies 128x128 int8/bf16 tiles
per cycle-ish; if the convolution maps onto it at even ~5% utilization
the constant changes by ~10x. (The reference's analogous move is
exploiting the widest multiplier available:
src/ballet/ed25519/avx512/fd_r43x6.h:10-32 — 52-bit IFMA lanes.)

The 2^13 limb scheme cannot half-split uniformly (13 is odd), so the
MXU formulations re-express elements in RADIX 2^7: 37 int8 digits
(pad to 40). Products of 7-bit digits are <=14 bits; 40-term
convolution sums stay < 2^20 — exact in int32 accumulation, which is
what the TPU's int8 MXU path produces natively.

Formulations measured (batch B lanes):

  vpu    roll-mac digit convolution in radix 2^7 (the control: same
         digit count, same unit of work, VPU lanes)
  toep   per-lane Toeplitz matrix built with jnp.roll, then ONE
         batched dot_general  C[b,k] = sum_i T[b,k,i] a[b,i]
         (int8 x int8 -> int32, contraction 40 — the MXU candidate)
  onehot Toeplitz build itself as a matmul against a CONSTANT one-hot
         tensor (b,40)@(40,79*40), then the batched matvec — both
         stages MXU, no per-lane roll chains

Each formulation is timed with the in-graph repeat methodology
(PERF.md: lax.fori_loop with data dependence so per-dispatch tunnel
latency amortizes) and byte-checked against the Python bigint oracle.

Run on the chip:  python tools/exp_mxu_fmul.py [--batch 1024] [--reps 64]
(on CPU it validates correctness; the ns numbers only mean something
on TPU hardware).
"""
import argparse
import time

import numpy as np

N_DIG = 40          # radix-2^7 digits (37 used, 3 slack)
OUT_DIG = 2 * N_DIG - 1


def to_digits(x: int) -> np.ndarray:
    return np.array([(x >> (7 * i)) & 0x7F for i in range(N_DIG)],
                    np.int8)


def from_digits(d) -> int:
    return sum(int(v) << (7 * i) for i, v in enumerate(np.asarray(d)))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=1024)
    ap.add_argument("--reps", type=int, default=64)
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp

    B = args.batch
    rng = np.random.default_rng(1)
    P = (1 << 255) - 19
    av = [int.from_bytes(rng.bytes(31), "little") for _ in range(B)]
    bv = [int.from_bytes(rng.bytes(31), "little") for _ in range(B)]
    A = jnp.asarray(np.stack([to_digits(x) for x in av]))   # (B, 40) i8
    Bm = jnp.asarray(np.stack([to_digits(x) for x in bv]))

    # --- formulations -----------------------------------------------------

    def conv_vpu(a, b):
        """Control: roll-mac convolution on the VPU (int32 lanes)."""
        a32 = a.astype(jnp.int32)
        b32 = b.astype(jnp.int32)
        acc = jnp.zeros((a.shape[0], OUT_DIG), jnp.int32)
        for i in range(N_DIG):
            term = a32[:, i:i + 1] * b32                    # (B, 40)
            acc = acc.at[:, i:i + N_DIG].add(term)
        return acc

    def conv_toep(a, b):
        """Per-lane Toeplitz + one batched int8 dot_general (MXU)."""
        # T[b, k, i] = b_digits[b, k - i]  (0 outside range)
        bz = jnp.pad(b, ((0, 0), (0, OUT_DIG - N_DIG)))     # (B, 79)
        rows = [jnp.roll(bz, i, axis=1) for i in range(N_DIG)]
        T = jnp.stack(rows, axis=2)                         # (B, 79, 40)
        # zero the wrapped tail of each roll
        mask = np.zeros((OUT_DIG, N_DIG), np.int8)
        for i in range(N_DIG):
            mask[i:i + N_DIG, i] = 1
        T = T * jnp.asarray(mask)[None]
        return jax.lax.dot_general(
            T, a, (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.int32)               # (B, 79)

    # constant one-hot shift tensor: S[j, k*40+i] = 1 iff k == i + j
    S_np = np.zeros((N_DIG, OUT_DIG * N_DIG), np.int8)
    for j in range(N_DIG):
        for i in range(N_DIG):
            S_np[j, (i + j) * N_DIG + i] = 1
    S = jnp.asarray(S_np)

    def conv_onehot(a, b):
        """Both stages as matmuls: Toeplitz build via the constant
        one-hot tensor, then the batched matvec."""
        T = jax.lax.dot_general(
            b, S, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32)               # (B, 79*40)
        T = T.reshape(b.shape[0], OUT_DIG, N_DIG).astype(jnp.int8)
        return jax.lax.dot_general(
            T, a, (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.int32)

    forms = {"vpu": conv_vpu, "toep": conv_toep, "onehot": conv_onehot}

    # --- correctness vs the bigint oracle ---------------------------------
    for name, fn in forms.items():
        out = np.asarray(jax.jit(fn)(A, Bm))
        for lane in (0, 1, B - 1):
            got = sum(int(v) << (7 * k) for k, v in enumerate(out[lane]))
            want = av[lane] * bv[lane]
            assert got == want, (name, lane)
        print(f"{name:7s} correctness ok (raw 510-bit products exact)")

    # --- timing (in-graph repeat, data-dependent) --------------------------
    dev = jax.devices()[0]
    print(f"platform: {dev.platform} ({dev.device_kind})")
    results = {}
    for name, fn in forms.items():
        def repeat(a, b, fn=fn):
            def body(_, carry):
                a, b = carry
                c = fn(a, b)
                # fold the output back into the inputs (data dependence)
                a2 = (a.astype(jnp.int32)
                      + c[:, :N_DIG]) % 127
                return a2.astype(jnp.int8), b
            a, b = jax.lax.fori_loop(0, args.reps, body, (a, b))
            return a
        jf = jax.jit(repeat)
        jf(A, Bm).block_until_ready()                       # compile
        t0 = time.perf_counter()
        jf(A, Bm).block_until_ready()
        dt = time.perf_counter() - t0
        ns = dt / args.reps / B * 1e9
        results[name] = ns
        print(f"{name:7s} {ns:8.2f} ns/fmul-conv/lane "
              f"({args.reps} reps, batch {B})")

    # --- verdict -----------------------------------------------------------
    base = results["vpu"]
    best = min(results, key=results.get)
    speedup = base / results[best]
    # the r3 Pallas roll-mac does the same convolution (radix 2^13) in
    # ~0.65 ns/lane; a formulation must beat the VPU control by >2x to
    # justify the radix-2^7 conversion overhead it drags into the kernel
    verdict = "GO" if best != "vpu" and speedup > 2.0 else "NO-GO"
    print(f"best={best} speedup_vs_vpu_control={speedup:.2f}x "
          f"-> {verdict} (decision threshold 2.0x; update PERF.md)")
    # machine-readable tail (the fdwitness stage contract: the LAST
    # JSON-object line of stdout is the stage result)
    import json
    print(json.dumps({
        "platform": dev.platform,
        "device_kind": getattr(dev, "device_kind", ""),
        "batch": B, "reps": args.reps,
        "ns_per_fmul_conv_lane": {k: round(v, 2)
                                  for k, v in results.items()},
        "mxu_best": best,
        "mxu_speedup_vs_vpu": round(speedup, 3),
        "mxu_threshold": 2.0,
        "mxu_verdict": verdict,
    }))


if __name__ == "__main__":
    main()
