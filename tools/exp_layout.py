"""Experiment: limb-major (NLIMB, batch) layout for field arithmetic.

Hypothesis: (batch, 20) arrays pad the minor dim 20 -> 128 lanes (84%
waste); transposing to (20, batch) makes batch the minor dim and should
speed up fe.mul / pt_dbl several-fold.
"""
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, ".")
from firedancer_tpu.ops import fe25519 as fe

NLIMB, BITS, MASK, FOLD = fe.NLIMB, fe.BITS, fe.MASK, fe.FOLD
BATCH = int(sys.argv[1]) if len(sys.argv) > 1 else 2048
R = 4


def carry_t(x):
    """(…limb axis 0…) transposed carry: limbs axis 0, batch axis 1."""
    for _ in range(3):
        lo = x & MASK
        hi = x >> BITS
        x = lo + jnp.concatenate([jnp.zeros_like(hi[:1]), hi[:-1]], axis=0)
        x = x.at[0].add(hi[-1] * FOLD)
    return x


def mul_t(a, b):
    prod = a[:, None, :] * b[None, :, :]              # (20,20,B)
    pad = jnp.concatenate([prod, jnp.zeros_like(prod)], axis=1)  # (20,40,B)
    flat = pad.reshape(2 * NLIMB * NLIMB, *prod.shape[2:])
    skew = flat[: NLIMB * (2 * NLIMB - 1)].reshape(
        NLIMB, 2 * NLIMB - 1, *prod.shape[2:])
    c = skew.sum(axis=0)                              # (39,B)
    lo = c & MASK
    hi = c >> BITS
    c = jnp.concatenate([lo, jnp.zeros_like(lo[:1])], axis=0)
    c = c.at[1:].add(hi)                              # (40,B)
    return carry_t(c[:NLIMB] + c[NLIMB:] * FOLD)


def mul_t_unrolled(a, b):
    """Fully unrolled accumulation: no outer-product materialization."""
    rows = []
    zero = jnp.zeros_like(a[0])
    for k in range(2 * NLIMB - 1):
        acc = zero
        for i in range(max(0, k - NLIMB + 1), min(NLIMB, k + 1)):
            acc = acc + a[i] * b[k - i]
        rows.append(acc)
    c = jnp.stack(rows, axis=0)                       # (39,B)
    lo = c & MASK
    hi = c >> BITS
    c = jnp.concatenate([lo, jnp.zeros_like(lo[:1])], axis=0)
    c = c.at[1:].add(hi)
    return carry_t(c[:NLIMB] + c[NLIMB:] * FOLD)


def add_t(a, b):
    return carry_t(a + b)


def sub_t(a, b):
    return carry_t(a + jnp.asarray(fe.SUB_C)[:, None] - b)


def mul_small_t(a, k):
    return carry_t(a * jnp.int32(k))


def sq_t(a, mul=mul_t):
    return mul(a, a)


def pt_dbl_t(p, mul=mul_t):
    x1, y1, z1, _ = p
    a = mul(x1, x1)
    b = mul(y1, y1)
    c = mul_small_t(mul(z1, z1), 2)
    h = add_t(a, b)
    xy = add_t(x1, y1)
    e = sub_t(h, mul(xy, xy))
    g = sub_t(a, b)
    f = add_t(c, g)
    return (mul(e, f), mul(g, h), mul(f, g), mul(e, h))


def timed(name, fn, x, iters=3):
    t0 = time.perf_counter()
    jax.block_until_ready(fn(x))
    comp = time.perf_counter() - t0
    best = 1e9
    for _ in range(iters):
        t0 = time.perf_counter()
        jax.block_until_ready(fn(x))
        best = min(best, time.perf_counter() - t0)
    print(f"{name:32s} {best/R*1e3:9.3f} ms/run  compile {comp:5.1f}s")
    return best / R


def main():
    rng = np.random.default_rng(0)
    a = jnp.asarray(rng.integers(0, 8192, (NLIMB, BATCH), dtype=np.int32))
    b = jnp.asarray(rng.integers(0, 8192, (NLIMB, BATCH), dtype=np.int32))
    print(f"batch={BATCH}")

    def mul64(v):
        for _ in range(64):
            v = mul_t(v, b)
        return v
    f = jax.jit(lambda v: jax.lax.fori_loop(0, R, lambda i, w: mul64(w), v))
    per = timed("mul_t x64 (skew)", f, a)
    print(f"  -> one fe.mul: {per/64*1e6:.0f} us")

    def mul64u(v):
        for _ in range(64):
            v = mul_t_unrolled(v, b)
        return v
    f = jax.jit(lambda v: jax.lax.fori_loop(0, R, lambda i, w: mul64u(w), v))
    per = timed("mul_t x64 (unrolled)", f, a)
    print(f"  -> one fe.mul: {per/64*1e6:.0f} us")

    pt = (a, b, mul_t(a, b), mul_t(b, b))
    def dbl64(p):
        q, _ = jax.lax.scan(lambda c, _: (pt_dbl_t(c), None), p, None, length=64)
        return q
    f = jax.jit(lambda p: jax.lax.fori_loop(
        0, R, lambda i, w: dbl64(w), p))
    per = timed("pt_dbl_t x64 (scan, skew)", f, pt)
    print(f"  -> one pt_dbl: {per/64*1e6:.0f} us")

    def dbl64u(p):
        q, _ = jax.lax.scan(
            lambda c, _: (pt_dbl_t(c, mul=mul_t_unrolled), None), p, None,
            length=64)
        return q
    f = jax.jit(lambda p: jax.lax.fori_loop(0, R, lambda i, w: dbl64u(w), p))
    per = timed("pt_dbl_t x64 (scan, unrolled)", f, pt)
    print(f"  -> one pt_dbl: {per/64*1e6:.0f} us")


if __name__ == "__main__":
    main()
