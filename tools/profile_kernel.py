"""Phase-level timing attribution for the ed25519 verify kernel on TPU.

Times each sub-phase of `verify_batch` separately (jitted, warmed) so we
know where the 685 ms/batch goes: SHA-512, decompression, the double
scalar-mul, and the final encode/invert. Run on the real chip:

    python tools/profile_kernel.py [batch] [msg_len]
"""
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, ".")
from firedancer_tpu.ops import ed25519 as ed
from firedancer_tpu.ops import fe25519 as fe
from firedancer_tpu.ops.sha2 import sha512

BATCH = int(sys.argv[1]) if len(sys.argv) > 1 else 2048
MSG_LEN = int(sys.argv[2]) if len(sys.argv) > 2 else 128


def bench(name, fn, *args, iters=4):
    t0 = time.perf_counter()
    out = jax.block_until_ready(fn(*args))
    compile_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    for _ in range(iters):
        out = jax.block_until_ready(fn(*args))
    dt = (time.perf_counter() - t0) / iters
    print(f"{name:28s} {dt*1e3:10.2f} ms  ({BATCH/dt:12.0f}/s)  compile {compile_s:6.1f}s")
    return out


def main():
    print(f"devices={jax.devices()} batch={BATCH} msg_len={MSG_LEN}")
    rng = np.random.default_rng(0)
    sig = jnp.asarray(rng.integers(0, 256, (BATCH, 64), dtype=np.uint8))
    pub = jnp.asarray(rng.integers(0, 256, (BATCH, 32), dtype=np.uint8))
    msg = jnp.asarray(rng.integers(0, 256, (BATCH, MSG_LEN), dtype=np.uint8))
    mlen = jnp.full((BATCH,), MSG_LEN, jnp.int32)

    # full kernel
    vb = jax.jit(lambda s, p, m, l: ed.verify_batch(s, p, m, l))
    bench("verify_batch (full)", vb, sig, pub, msg, mlen)

    # phase 1: sha512 of (R || A || msg)
    kmsg = jnp.concatenate([sig[:, :32], pub, msg], axis=-1)
    f_sha = jax.jit(lambda m, l: sha512(m, l))
    bench("sha512", f_sha, kmsg, mlen + 64)

    # phase 2: sc_reduce64
    dig = jax.block_until_ready(f_sha(kmsg, mlen + 64))
    f_red = jax.jit(ed.sc_reduce64)
    bench("sc_reduce64", f_red, dig)

    # phase 3: decompress (one pow chain)
    f_dec = jax.jit(lambda b: ed.decompress(b))
    bench("decompress(A)", f_dec, pub)

    # phase 4: double scalar mul
    k_digits = jax.block_until_ready(f_red(dig))
    s_digits, _ = ed.sc_from_bytes32(sig[:, 32:])
    a_pt, _ = jax.block_until_ready(f_dec(pub))
    s_w = jax.block_until_ready(jax.jit(ed.sc_windows4)(s_digits))
    k_w = jax.block_until_ready(jax.jit(ed.sc_windows4)(k_digits))

    f_dsm = jax.jit(lambda sw, kw, a: ed._double_scalar_mul(sw, kw, ed.pt_neg(a)))
    rp = bench("double_scalar_mul", f_dsm, s_w, k_w, a_pt)

    # phase 5: encode (invert chain + canonical)
    f_enc = jax.jit(ed.pt_tobytes)
    bench("pt_tobytes (invert+enc)", f_enc, rp)

    # micro: one field mul / one pt_add / one pt_dbl at batch
    a = jnp.asarray(rng.integers(0, 8192, (BATCH, fe.NLIMB), dtype=np.int32))
    b = jnp.asarray(rng.integers(0, 8192, (BATCH, fe.NLIMB), dtype=np.int32))
    f_mul = jax.jit(fe.mul)
    bench("fe.mul x1", f_mul, a, b, iters=20)

    def mul_chain(a, b):
        for _ in range(100):
            a = fe.mul(a, b)
        return a
    bench("fe.mul x100 (chain)", jax.jit(mul_chain), a, b)

    pt = (a_pt[0], a_pt[1], a_pt[2], a_pt[3])
    bench("pt_dbl x100", jax.jit(lambda p: _chain(ed.pt_dbl, p, 100)), pt)
    bench("pt_add x100",
          jax.jit(lambda p: _chain(lambda q: ed.pt_add(q, pt), p, 100)), pt)

    # pow chain alone
    bench("pow_const (p-5)/8", jax.jit(lambda x: fe.pow_const(x, (fe.P - 5) // 8)), a)


def _chain(f, p, n):
    for _ in range(n):
        p = f(p)
    return p


if __name__ == "__main__":
    main()
