"""Sanity-checked VPU throughput: vary inputs per call, check K scaling."""
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

ROWS, COLS = 256, 1024


def run(name, op, dtype, K):
    def kernel(a_ref, b_ref, o_ref):
        a = a_ref[:]

        def body(i, x):
            return op(x, a)
        o_ref[:] = jax.lax.fori_loop(0, K, body, b_ref[:])

    f = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((ROWS, COLS), dtype),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)] * 2,
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
    )
    g = jax.jit(lambda x, y: f(x, f(x, f(x, f(x, y)))))
    if dtype == jnp.float32:
        a = jnp.asarray(np.random.rand(ROWS, COLS) * 1e-8 + 1.0, dtype)
        b = jnp.asarray(np.random.rand(ROWS, COLS), dtype)
    else:
        a = jnp.asarray(np.random.randint(1, 100, (ROWS, COLS)), dtype)
        b = jnp.asarray(np.random.randint(0, 100, (ROWS, COLS)), dtype)
    jax.block_until_ready(g(a, b))
    best = 1e9
    for _ in range(4):
        b2 = b + np.random.randint(1, 10)      # new value each call
        t0 = time.perf_counter()
        jax.block_until_ready(g(a, b2))
        best = min(best, time.perf_counter() - t0)
    ops = ROWS * COLS * K * 4
    print(f"{name:18s} K={K:6d}  {best*1e3:8.2f} ms  {ops/best/1e9:8.0f} Gop/s")


for K in (1024, 8192):
    run("f32 mul", lambda x, a: x * a, jnp.float32, K)
    run("f32 fma", lambda x, a: x * a + a, jnp.float32, K)
    run("int32 mul", lambda x, a: x * a, jnp.int32, K)
    run("int32 add", lambda x, a: x + a, jnp.int32, K)
