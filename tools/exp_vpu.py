"""Raw VPU throughput: int32 mul vs f32 FMA vs bitwise, via Pallas chains.

Decides the field-element representation for the ed25519 Pallas kernel.
"""
import sys
import time
import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

ROWS, COLS = 256, 1024          # 1MB f32 block
K = 8192


def make_kernel(op):
    def kernel(a_ref, b_ref, o_ref):
        a = a_ref[:]
        b = b_ref[:]
        def body(i, x):
            return op(x, a, b)
        o_ref[:] = jax.lax.fori_loop(0, K, body, b)
    return kernel


def bench(name, op, dtype, iters=3, reps=8):
    if dtype == jnp.float32:
        a = jnp.asarray(np.random.rand(ROWS, COLS) * 0.001 + 1.0, dtype)
        b = jnp.asarray(np.random.rand(ROWS, COLS), dtype)
    else:
        a = jnp.asarray(np.random.randint(1, 3, (ROWS, COLS)), dtype)
        b = jnp.asarray(np.random.randint(0, 100, (ROWS, COLS)), dtype)
    f = pl.pallas_call(
        make_kernel(op),
        out_shape=jax.ShapeDtypeStruct((ROWS, COLS), dtype),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)] * 2,
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
    )
    def rep(x, y):
        o = f(x, y)
        for _ in range(reps - 1):
            o = f(x, o)
        return o
    g = jax.jit(rep)
    jax.block_until_ready(g(a, b))
    best = 1e9
    for _ in range(iters):
        t0 = time.perf_counter()
        jax.block_until_ready(g(a, b))
        best = min(best, time.perf_counter() - t0)
    ops = ROWS * COLS * K * reps
    print(f"{name:24s} {best*1e3:8.2f} ms  {ops/best/1e9:8.1f} Gelem-op/s")


def main():
    print(f"block {ROWS}x{COLS}, chain {K}")
    bench("f32 mul", lambda x, a, b: x * a, jnp.float32)
    bench("f32 fma (x*a+b)", lambda x, a, b: x * a + b, jnp.float32)
    bench("f32 add", lambda x, a, b: x + a, jnp.float32)
    bench("int32 mul", lambda x, a, b: x * a, jnp.int32)
    bench("int32 add", lambda x, a, b: x + a, jnp.int32)
    bench("int32 and", lambda x, a, b: x & a, jnp.int32)
    bench("int32 shr13", lambda x, a, b: (x >> 13) + a, jnp.int32)
    bench("int32 mul+add", lambda x, a, b: x * a + b, jnp.int32)
    bench("uint32 mul", lambda x, a, b: x * a, jnp.uint32)
    # f32 carry step: x - floor(x * inv) * r  (2 ops + floor)
    inv = 1.0 / 8192.0
    r = 8192.0
    bench("f32 carry (floor)", lambda x, a, b: x - jnp.floor(x * inv) * r + a,
          jnp.float32)


if __name__ == "__main__":
    main()
