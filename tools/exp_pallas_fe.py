"""Correctness + speed test of in-Pallas GF(2^255-19) mul formulations.

Variants:
  A: broadcast outer product (20,20,TB) + reshape-skew + sum
  B: row-broadcast products accumulated into (40,TB) via static slice adds
  C: row-broadcast products + pltpu.roll accumulate
Each wrapped in a kernel that chains NMUL muls to amortize launch+transfer.
"""
import functools
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

sys.path.insert(0, ".")
from firedancer_tpu.ops import fe25519 as fe

NLIMB, BITS, MASK, FOLD = fe.NLIMB, fe.BITS, fe.MASK, fe.FOLD
TB = int(sys.argv[1]) if len(sys.argv) > 1 else 512
NMUL = 1024


def carry3(x):
    """3-pass relaxed carry on (20, TB) int32 (no scatter: concat only)."""
    for _ in range(3):
        lo = x & MASK
        hi = x >> BITS
        x = lo + jnp.concatenate([hi[-1:] * FOLD, hi[:-1]], axis=0)
    return x


def reduce39(c):
    """(39, TB) coeffs -> carried (20, TB)."""
    lo = c & MASK
    hi = c >> BITS
    z1 = jnp.zeros_like(lo[:1])
    c = (jnp.concatenate([lo, z1], axis=0)
         + jnp.concatenate([z1, hi], axis=0))   # (40, TB)
    return carry3(c[:NLIMB] + c[NLIMB:] * FOLD)


def mul_a(a, b):
    prod = a[:, None, :] * b[None, :, :]                    # (20,20,TB)
    pad = jnp.concatenate([prod, jnp.zeros_like(prod)], axis=1)  # (20,40,TB)
    flat = pad.reshape(2 * NLIMB * NLIMB, prod.shape[-1])
    skew = flat[: NLIMB * (2 * NLIMB - 1)].reshape(
        NLIMB, 2 * NLIMB - 1, prod.shape[-1])
    return reduce39(skew.sum(axis=0))


def mul_b(a, b):
    acc = jnp.zeros((2 * NLIMB, a.shape[-1]), jnp.int32)
    for i in range(NLIMB):
        prod = a[i][None, :] * b                             # (20,TB)
        acc = acc + jnp.concatenate(
            [jnp.zeros((i, a.shape[-1]), jnp.int32), prod,
             jnp.zeros((NLIMB - i, a.shape[-1]), jnp.int32)], axis=0)
    return reduce39(acc[:2 * NLIMB - 1])


def mul_c(a, b):
    acc = jnp.zeros((2 * NLIMB, a.shape[-1]), jnp.int32)
    z = jnp.zeros((NLIMB, a.shape[-1]), jnp.int32)
    for i in range(NLIMB):
        prod = a[i][None, :] * b                             # (20,TB)
        padded = jnp.concatenate([prod, z], axis=0)          # (40,TB)
        acc = acc + pltpu.roll(padded, shift=i, axis=0)
    return reduce39(acc[:2 * NLIMB - 1])


def make_chain(mulfn):
    def kernel(a_ref, b_ref, o_ref):
        a = a_ref[:]
        b = b_ref[:]

        def body(i, x):
            return mulfn(x, b)
        o_ref[:] = jax.lax.fori_loop(0, NMUL, body, a)

    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((NLIMB, TB), jnp.int32),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)] * 2,
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
    )


def ref_chain(a, b):
    """Host oracle: NMUL sequential muls via python ints."""
    av = [fe.limbs_to_int(np.asarray(a)[:, j]) for j in range(a.shape[1])]
    bv = [fe.limbs_to_int(np.asarray(b)[:, j]) for j in range(b.shape[1])]
    out = []
    for x, y in zip(av, bv):
        for _ in range(NMUL):
            x = x * y % fe.P
        out.append(x)
    return out


def main():
    rng = np.random.default_rng(1)
    a = jnp.asarray(rng.integers(0, 8192, (NLIMB, TB), dtype=np.int32))
    b = jnp.asarray(rng.integers(0, 8192, (NLIMB, TB), dtype=np.int32))
    want = ref_chain(a[:, :4], b[:, :4])

    for name, mulfn in [("A reshape-skew", mul_a), ("B slice-acc", mul_b),
                        ("C roll-acc", mul_c)]:
        try:
            f = make_chain(mulfn)
            g = jax.jit(lambda x, y: f(f(f(f(x, y), y), y), y))
            t0 = time.perf_counter()
            out = np.asarray(g(a, b))
            compile_s = time.perf_counter() - t0
        except Exception as e:
            print(f"{name:18s} FAILED: {str(e)[:200]}")
            continue
        # correctness (single chain application = NMUL muls... g applies 4x)
        got1 = np.asarray(jax.jit(f)(a, b))
        ok = all(fe.limbs_to_int(got1[:, j]) % fe.P == want[j]
                 for j in range(4))
        best = 1e9
        for _ in range(3):
            t0 = time.perf_counter()
            np.asarray(g(a, b))
            best = min(best, time.perf_counter() - t0)
        nmul_total = NMUL * 4
        per_mul_ns_lane = best / nmul_total / TB * 1e9
        print(f"{name:18s} ok={ok}  {best*1e3:8.2f} ms total, "
              f"{per_mul_ns_lane:7.2f} ns/mul/lane, compile {compile_s:.1f}s")


if __name__ == "__main__":
    main()
