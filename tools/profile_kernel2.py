"""Dispatch-overhead-corrected phase attribution (in-graph repeats).

Each phase runs R times inside one jit via lax.fori_loop with data
dependence, so one dispatch amortizes the ~60 ms tunnel latency.

    python tools/profile_kernel2.py [batch] [msg_len] [repeats]
"""
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, ".")
from firedancer_tpu.ops import ed25519 as ed
from firedancer_tpu.ops import fe25519 as fe
from firedancer_tpu.ops.sha2 import sha512

BATCH = int(sys.argv[1]) if len(sys.argv) > 1 else 2048
MSG_LEN = int(sys.argv[2]) if len(sys.argv) > 2 else 128
R = int(sys.argv[3]) if len(sys.argv) > 3 else 8


def timed(name, fn, *args, iters=3):
    t0 = time.perf_counter()
    jax.block_until_ready(fn(*args))
    compile_s = time.perf_counter() - t0
    best = 1e9
    for _ in range(iters):
        t0 = time.perf_counter()
        jax.block_until_ready(fn(*args))
        best = min(best, time.perf_counter() - t0)
    per = best / R
    print(f"{name:28s} {per*1e3:9.2f} ms/run ({BATCH/per:12.0f}/s) "
          f"[dispatch {best*1e3:8.1f} ms, compile {compile_s:5.1f}s]")
    return per


def rep(body, x0):
    """Run body R times with data dependence inside one jit."""
    def f(x):
        return jax.lax.fori_loop(0, R, lambda i, v: body(v), x)
    return jax.jit(f), x0


def main():
    print(f"devices={jax.devices()} batch={BATCH} msg_len={MSG_LEN} repeats={R}")
    rng = np.random.default_rng(0)
    sig = jnp.asarray(rng.integers(0, 256, (BATCH, 64), dtype=np.uint8))
    pub = jnp.asarray(rng.integers(0, 256, (BATCH, 32), dtype=np.uint8))
    msg = jnp.asarray(rng.integers(0, 256, (BATCH, MSG_LEN), dtype=np.uint8))
    mlen = jnp.full((BATCH,), MSG_LEN, jnp.int32)

    # overhead: trivial op
    f0, x0 = rep(lambda v: v + 1, jnp.zeros((8,), jnp.int32))
    t0 = time.perf_counter(); jax.block_until_ready(f0(x0))
    t0 = time.perf_counter(); jax.block_until_ready(f0(x0))
    print(f"dispatch overhead (trivial):  {(time.perf_counter()-t0)*1e3:.1f} ms")

    # full verify, repeated with perturbed message so nothing is elided
    def vb_body(m):
        ok = ed.verify_batch(sig, pub, m, mlen)
        return m.at[:, 0].set(ok.astype(jnp.uint8))
    f, x = rep(vb_body, msg)
    timed("verify_batch (full)", f, x, iters=2)

    # sha512 at msg len
    kmsg = jnp.concatenate([sig[:, :32], pub, msg], axis=-1)
    def sha_body(m):
        d = sha512(m, mlen + 64)
        return m.at[:, 0].set(d[:, 0])
    f, x = rep(sha_body, kmsg)
    timed("sha512", f, x)

    # sc_reduce64
    dig = jax.block_until_ready(jax.jit(sha512)(kmsg, mlen + 64))
    def red_body(d):
        z = ed.sc_reduce64(d)
        return d.at[:, 0].set(z[:, 0].astype(jnp.uint8))
    f, x = rep(red_body, dig)
    timed("sc_reduce64", f, x)

    # decompress
    def dec_body(b):
        (xx, yy, zz, tt), ok = ed.decompress(b)
        return b.at[:, 0].set(xx[:, 0].astype(jnp.uint8))
    f, x = rep(dec_body, pub)
    timed("decompress(A)", f, x)

    # double scalar mul
    s_digits, _ = ed.sc_from_bytes32(sig[:, 32:])
    k_digits = jax.block_until_ready(jax.jit(ed.sc_reduce64)(dig))
    a_pt, _ = jax.block_until_ready(jax.jit(lambda b: ed.decompress(b))(pub))
    s_w = jax.block_until_ready(jax.jit(ed.sc_windows4)(s_digits))
    k_w = jax.block_until_ready(jax.jit(ed.sc_windows4)(k_digits))

    def dsm_body(sw):
        p = ed._double_scalar_mul(sw, k_w, ed.pt_neg(a_pt))
        return sw.at[:, 0].set(p[0][:, 0])
    f, x = rep(dsm_body, s_w)
    timed("double_scalar_mul", f, x, iters=2)

    # encode
    rp = jax.block_until_ready(jax.jit(
        lambda sw, kw: ed._double_scalar_mul(sw, kw, ed.pt_neg(a_pt)))(s_w, k_w))
    def enc_body(p):
        b = ed.pt_tobytes(p)
        return tuple(c.at[..., 0].set(b[:, 0].astype(jnp.int32)) for c in p)
    f, x = rep(enc_body, rp)
    timed("pt_tobytes (invert+enc)", f, x)

    # micro: fe.mul chain of 64 inside fori body
    a = jnp.asarray(rng.integers(0, 8192, (BATCH, fe.NLIMB), dtype=np.int32))
    b = jnp.asarray(rng.integers(0, 8192, (BATCH, fe.NLIMB), dtype=np.int32))
    def mul64(v):
        for _ in range(64):
            v = fe.mul(v, b)
        return v
    f, x = rep(mul64, a)
    per = timed("fe.mul x64", f, x)
    print(f"  -> one batched fe.mul: {per/64*1e6:.0f} us "
          f"({per/64/BATCH*1e9:.1f} ns/lane)")

    # micro: pt_dbl / pt_add chains of 16 (scan to bound compile)
    def dbln(p):
        q, _ = jax.lax.scan(lambda c, _: (ed.pt_dbl(c), None), p, None, length=64)
        return q
    f, x = rep(dbln, a_pt)
    per = timed("pt_dbl x64 (scan)", f, x)
    print(f"  -> one batched pt_dbl: {per/64*1e6:.0f} us")

    def addn(p):
        q, _ = jax.lax.scan(lambda c, _: (ed.pt_add(c, a_pt), None), p, None,
                            length=64)
        return q
    f, x = rep(addn, a_pt)
    per = timed("pt_add x64 (scan)", f, x)
    print(f"  -> one batched pt_add: {per/64*1e6:.0f} us")

    # pow chain
    def powb(v):
        return fe.pow_const(v, (fe.P - 5) // 8)
    f, x = rep(powb, a)
    timed("pow_const (p-5)/8", f, x)


if __name__ == "__main__":
    main()
