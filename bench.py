"""Headline benchmark: batched ed25519 sigverify throughput on one chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.

Baseline: the reference's wiredancer FPGA sigverify tile sustains ~1M
verifies/s on one AWS-F1 card, vs ~30K/s per Skylake core for the C path
(ref: src/wiredancer/README.md:99-119). BASELINE.json's north star for this
rebuild is >= 1M ed25519 verifies/s on a single TPU chip, so
vs_baseline = verifies_per_sec / 1e6.

Methodology mirrors the reference's unit-test self-benchmarks
(ref: src/ballet/ed25519/test_ed25519.c:26-31 — print throughput from a
tight loop over pre-generated valid signatures): pre-generate distinct
signed messages host-side, tile to the microbatch size, jit-compile once,
then time steady-state iterations end-to-end (device dispatch + compute +
verdict readback). Per-iteration wall times give p99 dispatch latency.

Resilience: the TPU backend ("axon" PJRT plugin over a tunnel) can fail or
hang at init. The parent process therefore runs the measurement in a child
with a bounded deadline; on failure it retries with the CPU backend forced,
and ALWAYS emits exactly one JSON line (value 0 + "error" when everything
failed). The recorded "platform" field says what actually ran.
"""
import hashlib
import json
import os
import subprocess
import sys
import tempfile
import time

import numpy as np

HERE = os.path.dirname(os.path.abspath(__file__))
BASELINE_VPS = 1.0e6


def _gen_vectors(n_unique: int, max_len: int, rng: np.random.Generator):
    from firedancer_tpu.utils.ed25519_ref import keypair, sign

    sig = np.zeros((n_unique, 64), np.uint8)
    pub = np.zeros((n_unique, 32), np.uint8)
    msg = np.zeros((n_unique, max_len), np.uint8)
    ln = np.zeros((n_unique,), np.int32)
    for i in range(n_unique):
        seed = hashlib.sha256(b"bench-key-%d" % (i % 8)).digest()
        m = rng.integers(0, 256, size=(int(rng.integers(32, max_len)),),
                         dtype=np.uint8).tobytes()
        _, _, pk = keypair(seed)
        s = sign(seed, m)
        sig[i] = np.frombuffer(s, np.uint8)
        pub[i] = np.frombuffer(pk, np.uint8)
        msg[i, :len(m)] = np.frombuffer(m, np.uint8)
        ln[i] = len(m)
    return sig, pub, msg, ln


def _child_bench():
    """Run the measurement on whatever backend this process resolves.

    Prints one JSON line on success; any exception propagates (the parent
    handles fallback + reporting)."""
    import jax
    import jax.numpy as jnp

    if os.environ.get("FDTPU_BENCH_FORCE_CPU") == "1":
        # sitecustomize latched the axon platform before our env mattered
        jax.config.update("jax_platforms", "cpu")

    sys.path.insert(0, HERE)
    from firedancer_tpu.ops import ed25519 as ed

    jax.config.update("jax_compilation_cache_dir",
                      os.path.join(HERE, ".jax_cache"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

    dev = jax.devices()[0]
    platform = dev.platform
    on_tpu = platform != "cpu"
    batch = int(os.environ.get("FDTPU_BENCH_BATCH",
                               "8192" if on_tpu else "64"))
    # MTU-realistic message length: the verify path must handle txn MTU
    # 1232 (ref: src/ballet/txn/fd_txn.h:102-104)
    max_len = int(os.environ.get("FDTPU_BENCH_MSG_LEN", "1232"))
    n_unique = min(batch, 256)

    rng = np.random.default_rng(42)
    sig, pub, msg, ln = _gen_vectors(n_unique, max_len, rng)
    reps = -(-batch // n_unique)
    sig = np.tile(sig, (reps, 1))[:batch]
    pub = np.tile(pub, (reps, 1))[:batch]
    msg = np.tile(msg, (reps, 1))[:batch]
    ln = np.tile(ln, reps)[:batch]

    if on_tpu:
        # fused Pallas kernels (ops/pallas_ed.py) — the production path
        from firedancer_tpu.ops import pallas_ed as ped
        fn = jax.jit(lambda s, p, m, l: ped.verify_batch(s, p, m, l))
        kernel = "pallas"
    else:
        fn = jax.jit(ed.verify_batch)
        kernel = "jnp"
    args = (jnp.asarray(sig), jnp.asarray(pub), jnp.asarray(msg),
            jnp.asarray(ln))
    t0 = time.perf_counter()
    out = fn(*args)
    out.block_until_ready()
    compile_s = time.perf_counter() - t0
    assert bool(np.asarray(out).all()), "bench vectors failed to verify"

    iters = int(os.environ.get("FDTPU_BENCH_ITERS", "16" if on_tpu else "2"))
    # per-dispatch (blocking) latency for p99
    lat = []
    for _ in range(max(4, iters // 4)):
        t1 = time.perf_counter()
        fn(*args).block_until_ready()
        lat.append(time.perf_counter() - t1)
    # steady-state throughput: pipelined dispatch (async queue, block at
    # the end) — how the verify tile actually drives the chip, and the
    # methodology that hides the tunnel's per-dispatch latency
    t0 = time.perf_counter()
    outs = [fn(*args) for _ in range(iters)]
    jax.block_until_ready(outs)
    dt = time.perf_counter() - t0

    vps = batch * iters / dt
    out_rec = {
        "metric": "ed25519_verifies_per_sec",
        "value": round(vps, 1),
        "unit": "verifies/s/chip",
        "vs_baseline": round(vps / BASELINE_VPS, 4),
        "platform": platform,
        "kernel": kernel,
        "batch": batch,
        "iters": iters,
        "msg_len": max_len,
        "p99_batch_ms": round(sorted(lat)[min(len(lat) - 1,
                                              -(-len(lat) * 99 // 100) - 1)]
                              * 1e3, 2),
        "compile_s": round(compile_s, 1),
    }

    if os.environ.get("FDTPU_BENCH_SKIP_RLC") != "1":
        # bulk pre-filter path: RLC batch verification (cofactored
        # semantics — ops/pallas_msm.py docstring), the ROADMAP 1b
        # rlc_bulk_vps stanza. The hardware run doubles as the
        # kernel's correctness gate: the all-valid batch must pass,
        # and a forged lane must fail it. On CPU the jnp limb kernel
        # runs a SMALL batch (the MSM graph compiles in minutes and
        # verifies a few hundred lanes/s — the number is recorded for
        # the platform, the witnessed-fallback carries the chip one)
        # so CPU-only CI still exercises + records the stanza.
        try:
            rfn = ed.rlc_verify_fn()   # shared platform dispatch
            if on_tpu:
                rbatch, rargs = batch, args
            else:
                rbatch = min(batch, int(os.environ.get(
                    "FDTPU_BENCH_RLC_CPU_BATCH", "16")))
                rargs = tuple(a[:rbatch] for a in args)
            zrng = np.random.default_rng(7)
            z = jnp.asarray(zrng.integers(0, 256, (rbatch, 16),
                                          dtype=np.uint8))
            t0 = time.perf_counter()
            ok, pre = rfn(*rargs, z)
            jax.block_until_ready((ok, pre))
            rlc_compile_s = time.perf_counter() - t0
            assert bool(ok) and bool(np.asarray(pre).all()), \
                "rlc: valid batch failed"
            bad_msg = np.array(msg[:rbatch])
            bad_msg[3, 0] ^= 0x01          # forge lane 3's message:
            ok2, _ = rfn(rargs[0], rargs[1],  # prechecks still pass,
                         jnp.asarray(bad_msg),  # the equation must not
                         rargs[3], z)
            assert not bool(ok2), \
                "rlc: forged lane not caught by the batch equation"
            riters = iters if on_tpu else max(2, iters)
            t0 = time.perf_counter()
            outs = [rfn(*rargs, z) for _ in range(riters)]
            jax.block_until_ready(outs)
            rdt = time.perf_counter() - t0
            out_rec["rlc_bulk_vps"] = round(rbatch * riters / rdt, 1)
            out_rec["rlc_bulk_batch"] = rbatch
            out_rec["rlc_compile_s"] = round(rlc_compile_s, 1)
        except Exception as e:  # noqa: BLE001 — annotate, don't break
            out_rec["rlc_error"] = f"{e!r}"[:200]

    print(json.dumps(out_rec))
    sys.stdout.flush()


def _bench_flight(stage: str):
    """[flight] cfg for a bench stage topology, or None. Under
    FDTPU_BENCH_FLIGHT_DIR each stage archives its telemetry history
    to <dir>/<stage>, so report.html's history tab (and fdflight
    post-mortems) cover the bench run itself. Off by default — the
    recorder is reader-side only, but the bench path stays untouched
    unless asked."""
    root = os.environ.get("FDTPU_BENCH_FLIGHT_DIR")
    if not root:
        return None
    return {"dir": os.path.join(root, stage)}


def _e2e_run(count: int, unique: int, batch: int,
             rate_tps: float = 0.0, coalesce_us: float = 0.0,
             profile: bool = True):
    """One synth -> verify -> dedup -> sink topology run; returns the
    measured record (tps, stage budget, link budget, and — with
    profile=True — the fdprof per-stage attribution digest). rate_tps
    > 0 paces the synth (the offered axis of the sweep); 0 lets it rip
    (capacity measurement)."""
    from firedancer_tpu.disco import Topology, TopologyRunner
    from firedancer_tpu.disco.metrics import (link_lag, merge_hists,
                                              quantile_ns, read_hists,
                                              read_link_metrics)

    # bench observatory (fdprof): low prime sampling rate so the
    # profile rides every bench round at negligible overhead (the
    # tier-1 overhead test bounds the sampler; 29 Hz against ~20 us
    # polls is noise) — override/disable with FDTPU_BENCH_PROF_HZ
    prof_hz = float(os.environ.get("FDTPU_BENCH_PROF_HZ", "29"))
    prof_cfg = {"enable": True, "hz": prof_hz} \
        if profile and prof_hz > 0 else None
    flight_cfg = _bench_flight("e2e")
    topo = (
        Topology(f"bench{os.getpid()}", wksp_size=1 << 26,
                 prof=prof_cfg, flight=flight_cfg)
        .link("ingest", depth=8192, mtu=1280)
        .link("verify_dedup", depth=8192, mtu=1280)
        .link("dedup_sink", depth=8192, mtu=1280)
        .tcache("verify_tc", depth=8192)
        .tcache("dedup_tc", depth=8192)
        .tile("synth", "synth", outs=["ingest"], count=count,
              unique=unique, burst=1024, seed=17, rate_tps=rate_tps)
        .tile("verify", "verify", ins=["ingest"], outs=["verify_dedup"],
              batch=batch, tcache="verify_tc", coalesce_us=coalesce_us)
        .tile("dedup", "dedup", ins=["verify_dedup"], outs=["dedup_sink"],
              tcache="dedup_tc", batch=1024)
        .tile("sink", "sink", ins=["dedup_sink"], batch=1024)
    )
    if flight_cfg:
        topo.tile("flight", "flight")
    runner = TopologyRunner(topo.build()).start()
    try:
        runner.wait_running(timeout_s=840)   # includes verify compile
        t0 = time.perf_counter()
        runner.wait_idle("sink", "rx", unique, timeout_s=600)
        runner.wait_idle("verify", "rx", count, timeout_s=600)
        wall = time.perf_counter() - t0
        hists = read_hists(runner.wksp, runner.plan, "verify")
        p99_ms = quantile_ns(hists.get("work", {"count": 0}), 0.99) / 1e6 \
            if hists else 0.0
        # stage-by-stage latency/occupancy budget (VERDICT r4 item 2):
        # per tile, p50/p99 of busy poll iterations and the fraction of
        # wall time spent working vs waiting on the ring
        budget = {}
        for t in ("synth", "verify", "dedup", "sink"):
            h = read_hists(runner.wksp, runner.plan, t)
            if not h:
                continue
            work, wait = h.get("work"), h.get("wait")
            tot_work = work["sum_ns"] if work else 0
            tot_wait = wait["sum_ns"] if wait else 0
            busy = tot_work / (tot_work + tot_wait) \
                if tot_work + tot_wait else 0.0
            budget[t] = {
                "work_p50_us": round(quantile_ns(work, 0.50) / 1e3, 1)
                if work else 0,
                "work_p99_us": round(quantile_ns(work, 0.99) / 1e3, 1)
                if work else 0,
                "occupancy": round(busy, 3),
            }
        # per-link attribution (fdmetrics v2): WHERE the hot-path time
        # and backpressure went, hop by hop — published/consumed (loss
        # per hop), producer backpressure ticks, and the consumer-side
        # consume-latency quantiles — so the bench trajectory records
        # which hop throttles end-to-end TPS, not just the number
        link_budget = {}
        for ln, rec in read_link_metrics(runner.wksp,
                                         runner.plan).items():
            cons = rec["consumers"]
            # link-level quantiles over ALL consumers (rr-sharded
            # verify), loss = the shared per-consumer lag definition
            h = merge_hists(c["hist"] for c in cons.values())
            link_budget[ln] = {
                "pub": rec["pub"],
                "consumed": sum(c["consumed"] for c in cons.values()),
                "lost": sum(link_lag(rec, tn) for tn in cons),
                "backpressure": rec["backpressure"],
                "consume_p50_us": round(quantile_ns(h, 0.50) / 1e3, 1)
                if h else 0,
                "consume_p99_us": round(quantile_ns(h, 0.99) / 1e3, 1)
                if h else 0,
            }
        out = {
            "e2e_tps": round(count / wall, 1),
            "e2e_count": count,
            "e2e_wall_s": round(wall, 2),
            "e2e_verify_work_p99_ms": round(p99_ms, 2),
            "e2e_stage_budget": budget,
            "e2e_link_budget": link_budget,
        }
        if prof_cfg:
            # per-stage profile digest (fdprof): top-k frames with
            # stem-state attribution, device occupancy (tpu time /
            # wall), compile counts — the WHY next to every number,
            # diffable across rounds by tools/fdbench
            from firedancer_tpu.prof import profile_summary
            prof = profile_summary(runner.plan, runner.wksp)
            vh = read_hists(runner.wksp, runner.plan, "verify")
            tpu = vh.get("tpu", {"sum_ns": 0})
            vm = runner.metrics("verify")
            prof["verify_device"] = {
                "occupancy": round(tpu["sum_ns"] / 1e9 / wall, 3)
                if wall else 0.0,
                "compiles": vm.get("tpu_jit_compiles", 0),
                "cache_miss": vm.get("tpu_jit_cache_miss", 0),
                "compile_s": round(
                    vm.get("tpu_compile_ns", 0) / 1e9, 2),
            }
            out["e2e_profile"] = prof
        return out
    finally:
        runner.halt()
        runner.close()


def _saturating_hop(rec: dict):
    """Attribute a sweep point's bottleneck: the highest-occupancy tile
    and the first link (in hop order) showing producer backpressure —
    the two answers 'which hop saturates first' decomposes into."""
    budget = rec.get("e2e_stage_budget", {})
    top_tile = max(budget, key=lambda t: budget[t]["occupancy"]) \
        if budget else None
    links = rec.get("e2e_link_budget", {})
    bp_link = None
    for ln in ("ingest", "verify_dedup", "dedup_sink"):
        if links.get(ln, {}).get("backpressure", 0) > 0:
            bp_link = ln
            break
    return top_tile, bp_link


def _e2e_bench():
    """End-to-end tile pipeline TPS on the resolved backend: synth ->
    verify(device) -> dedup -> sink across four OS processes over shm
    rings (BASELINE config 3/4 — the verify-tile replay measurement;
    ref: src/app/shared_dev/commands/bench/ bencho TPS observation).

    Prints one JSON line: {"e2e_tps", "e2e_count", "e2e_wall_s",
    "e2e_verify_work_p99_ms", "e2e_offered_sweep", "e2e_knee_tps",
    "platform"}. TPS counts frags INGESTED by the verify tile (rx,
    incl. dup drops — the tile's real workload); the clock starts when
    every tile reaches RUN (compile excluded) and stops when the last
    unique txn reaches the sink.

    The offered-load sweep (r10) re-runs the topology with the synth
    paced at fractions of the measured capacity and records, per
    point, achieved-vs-offered plus which hop saturated first (top
    occupancy tile, first backpressured link). The knee — the highest
    offered load still served at >= 90% — is the number future PRs
    must move, and the per-point hop attribution says what to fix.

    NOTE: this process must NOT initialize the jax backend — the verify
    tile's process owns the (exclusive) device tunnel; platform is
    inferred from the env the tiles will see."""
    sys.path.insert(0, HERE)
    # sizing against the ~60 ms tunnel dispatch latency: throughput
    # ceiling ~= batch * inflight / latency, so 2048 * 3 / 60ms ~= 100K
    # frags/s of device headroom; the ingest ring must hold several
    # in-flight batches or the batch can never fill (VERDICT r4 item 2)
    count = int(os.environ.get("FDTPU_BENCH_E2E_COUNT", "65536"))
    unique = int(os.environ.get("FDTPU_BENCH_E2E_UNIQUE", "256"))
    batch = int(os.environ.get("FDTPU_BENCH_E2E_BATCH", "2048"))
    coalesce_us = float(os.environ.get("FDTPU_BENCH_E2E_COALESCE_US",
                                       "500"))
    os.environ.setdefault("FDTPU_VERIFY_INFLIGHT", "3")
    out = _e2e_run(count, unique, batch, coalesce_us=coalesce_us)
    out["platform"] = os.environ.get("FDTPU_JAX_PLATFORM") or "device"

    # offered-load sweep: fractions of the measured capacity (override:
    # FDTPU_BENCH_E2E_SWEEP="0.5,0.8,1.1" — empty string disables)
    fracs_env = os.environ.get("FDTPU_BENCH_E2E_SWEEP", "0.5,0.8,1.2")
    fracs = [float(f) for f in fracs_env.split(",") if f.strip()]
    if fracs:
        cap = out["e2e_tps"]
        sweep = []
        for frac in fracs:
            offered = cap * frac
            # ~2 s of traffic per point, floored so the batch pipeline
            # actually engages; compile is warm from the first run
            n_pt = int(max(8192, min(count, offered * 2)))
            try:
                # sweep points keep only achieved/hop attribution —
                # skip the per-point profiling the capacity run did
                rec = _e2e_run(n_pt, unique, batch, rate_tps=offered,
                               coalesce_us=coalesce_us, profile=False)
            except Exception as e:  # noqa: BLE001 — annotate the point
                sweep.append({"offered_tps": round(offered, 1),
                              "error": f"{e!r}"[:200]})
                continue
            top_tile, bp_link = _saturating_hop(rec)
            sweep.append({
                "offered_tps": round(offered, 1),
                "achieved_tps": rec["e2e_tps"],
                "served_frac": round(rec["e2e_tps"] / offered, 3)
                if offered else 0.0,
                "top_occupancy_tile": top_tile,
                "first_backpressured_link": bp_link,
            })
        out["e2e_offered_sweep"] = sweep
        served = [p for p in sweep if p.get("served_frac", 0) >= 0.9]
        # no point served >= 90% (all errored, or pacing never kept
        # up): the knee is UNKNOWN-BAD, reported null — falling back
        # to raw capacity would report the most optimistic number
        # exactly when the sweep proved no offered load is sustained
        knee = max((p["achieved_tps"] for p in served), default=None)
        out["e2e_knee_tps"] = round(knee, 1) if knee is not None else None
    print(json.dumps(out))
    sys.stdout.flush()


def _leader_topology(count, unique, batch, verify_tiles, rate_tps,
                     tcache_depth=None):
    """The FULL leader loop: synth -> verify(xN, rr-sharded) -> dedup
    -> pack -> bank(svm device waves) -> poh -> shred(leader, signed
    merkle FEC sets) -> shredsink. Tcache depths sit BELOW the unique
    txn pool so replayed frames re-verify and re-execute instead of
    dedup-dropping — the loop sees `count` txns of real work from a
    pool it can afford to pre-sign at boot. Verify shards get a core
    each (cpu0+i) when the host has cores to spare."""
    from firedancer_tpu.disco import Topology
    if tcache_depth is None:
        # the wraparound trick needs a replay's tag EVICTED by the
        # time its copy is queried. Eviction happens at insert
        # (finalize) but queries happen at dispatch, so the effective
        # window is depth + the verify in-flight window (~batch x
        # (inflight+1) lanes) — and rr sharding divides the replay
        # distance by tile_cnt. depth ~ unique/16 leaves comfortable
        # margin for all of that; below 16 the tcache degenerates
        tcache_depth = max(16, 1 << (max(64, int(unique)).bit_length()
                                     - 4))
    cpus = os.cpu_count() or 1
    cpu0 = 1 if cpus >= verify_tiles + 6 else None
    vd = [f"vd{i}" for i in range(verify_tiles)]
    flight_cfg = _bench_flight("leader")
    topo = (
        Topology(f"ldr{os.getpid()}", wksp_size=1 << 27,
                 flight=flight_cfg)
        .link("ingest", depth=4096, mtu=1280)
        .link("dedup_pack", depth=4096, mtu=1280)
        .link("pack_bank0", depth=256, mtu=16384)
        .link("bank0_done", depth=256, mtu=64)
        .link("bank0_poh", depth=256, mtu=16448)
        .link("poh_entries", depth=512, mtu=16640)
        .link("poh_slots", depth=64, mtu=64)
        .link("shreds_mirror", depth=4096, mtu=1280)
        .link("shred_req", depth=32, mtu=1280)
        .link("sign_resp", depth=32, mtu=128)
        .tcache("dedup_tc", depth=tcache_depth)
        .tile("synth", "synth", outs=["ingest"], count=count,
              unique=unique, burst=512, seed=17, rate_tps=rate_tps)
        .tile("dedup", "dedup", ins=vd, outs=["dedup_pack"],
              tcache="dedup_tc", batch=1024)
        .tile("pack", "pack",
              ins=["dedup_pack", "bank0_done", "poh_slots"],
              outs=["pack_bank0"], txn_in="dedup_pack",
              bank_links=["pack_bank0"], done_links=["bank0_done"],
              slot_in="poh_slots", max_txn_per_microblock=31,
              wave=4, batch=256)
        .tile("bank0", "bank", ins=["pack_bank0"],
              outs=["bank0_done", "bank0_poh"], exec="svm", wave=8,
              poh_link="bank0_poh", forward_payloads=True,
              genesis_synth=unique)
        .tile("poh", "poh", ins=["bank0_poh"],
              outs=["poh_entries", "poh_slots"],
              slot_link="poh_slots", hashes_per_tick=64,
              ticks_per_slot=8)
        .tile("shred", "shred", mode="leader",
              ins=["poh_entries", ("sign_resp", False)],
              outs=["shred_req", "shreds_mirror"], req="shred_req",
              resp="sign_resp", shreds_link="shreds_mirror",
              identity_hex="03a107bff3ce10be1d70dd18e74bc09967e4d63"
                           "09ba50d5f1ddc8664125531b8",
              cluster=[{"pubkey_hex": "55" * 32, "stake": 100,
                        "addr": "127.0.0.1:9"}])
        .tile("sign", "sign", ins=[("shred_req", False)],
              outs=["sign_resp"],
              seed="000102030405060708090a0b0c0d0e0f10111213141516"
                   "1718191a1b1c1d1e1f",
              clients=[{"role": "leader", "req": "shred_req",
                        "resp": "sign_resp"}])
        .tile("shredsink", "sink", ins=["shreds_mirror"]))
    if flight_cfg:
        topo.tile("flight", "flight")
    for i in range(verify_tiles):
        topo.link(vd[i], depth=4096, mtu=1280)
        topo.tcache(f"vtc{i}", depth=tcache_depth)
    topo.sharded_tile(
        "verify", "verify", verify_tiles, ins=["ingest"], outs=vd,
        batch=batch, coalesce_us=500, cpu0=cpu0,
        tcache=[f"vtc{i}" for i in range(verify_tiles)])
    return topo


_LEADER_TILES = ("synth", "dedup", "pack", "bank0", "poh", "shred",
                 "shredsink")
_LEADER_LINKS = ("ingest", "dedup_pack", "pack_bank0", "bank0_poh",
                 "poh_entries", "shreds_mirror")


def _leader_hop_snapshot(runner, verify_tiles, tiles_extra=()):
    """Cumulative per-tile work/wait sums + per-link backpressure —
    diffed per sweep stanza to attribute the saturating hop.
    tiles_extra: additional tile names beyond the canonical leader set
    (the r16 exec-family loop adds resolv + exec shards)."""
    from firedancer_tpu.disco.metrics import (read_hists,
                                              read_link_metrics)
    tiles = {}
    names = list(_LEADER_TILES) + [f"verify{i}"
                                   for i in range(verify_tiles)] \
        + list(tiles_extra)
    for t in names:
        h = read_hists(runner.wksp, runner.plan, t)
        if not h:
            continue
        tiles[t] = (h.get("work", {}).get("sum_ns", 0),
                    h.get("wait", {}).get("sum_ns", 0))
    links = {ln: rec["backpressure"]
             for ln, rec in read_link_metrics(runner.wksp,
                                              runner.plan).items()}
    return {"tiles": tiles, "links": links}


def _leader_hop(prev, cur, verify_tiles, links_extra=()):
    """(top occupancy tile, first backpressured link) over a stanza
    window, from two cumulative snapshots."""
    occ = {}
    for t, (w1, i1) in cur["tiles"].items():
        w0, i0 = prev["tiles"].get(t, (0, 0))
        dw, di = w1 - w0, i1 - i0
        occ[t] = dw / (dw + di) if dw + di else 0.0
    top = max(occ, key=occ.get) if occ else None
    link_order = ["ingest"] + [f"vd{i}" for i in range(verify_tiles)] \
        + [ln for ln in _LEADER_LINKS if ln != "ingest"] \
        + list(links_extra)
    bp = next((ln for ln in link_order
               if cur["links"].get(ln, 0)
               - prev["links"].get(ln, 0) > 0), None)
    return top, bp


def _leader_wait_drained(runner, count, verify_tiles,
                         timeout_s=600.0, resolv=False):
    """Block until every synth txn reached a TERMINAL outcome
    (executed by the bank, or dropped at a named hop — conservation
    accounting, so a still-chewing pipeline is never mistaken for a
    drained one) and pack has retired every outstanding microblock.
    resolv=True adds the r16 resolv tile's drop counters to the
    conservation sum (the exec-family loop runs it ahead of pack)."""
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        runner.check_failures()
        p = runner.metrics("pack")
        b = runner.metrics("bank0")
        dropped = runner.metrics("dedup")["dup"] + p["parse_fail"]
        if resolv:
            r = runner.metrics("resolv")
            dropped += r["parse_fail"] + r["alut_fail"] \
                + r["fee_fail"] + r["oversz"]
        for i in range(verify_tiles):
            v = runner.metrics(f"verify{i}")
            dropped += v["parse_fail"] + v["dedup_drop"] \
                + v["verify_fail"]
        if b["txns"] + dropped >= count \
                and p["completions"] == p["microblocks"]:
            return
        time.sleep(0.1)
    raise TimeoutError(f"leader loop never drained: pack={p} bank={b}")


def _leader_bench():
    """Leader-loop sweep stage (r13): measure the knee of the WHOLE
    leader loop — the number that has to survive millions of users —
    not just synth->verify->dedup->sink.

    Two boots: (1) unpaced capacity (the loop's ceiling, bank-executed
    txns per wall second from RUN to drained); (2) ONE ramped boot for
    every sweep point (the synth's rate_tps ramp schedule holds each
    offered load for a fixed stanza), recording per stanza the
    achieved rate and the saturating hop (top-occupancy tile + first
    link showing fresh backpressure). Knee = highest offered load
    still served at >= 90%.

    Prints one JSON line with e2e_leader_tps / e2e_leader_sweep /
    e2e_leader_knee_tps / e2e_leader_hop. The parent process must not
    touch jax — the verify tile processes own the device."""
    sys.path.insert(0, HERE)
    from firedancer_tpu.disco import TopologyRunner
    count = int(os.environ.get("FDTPU_BENCH_LEADER_COUNT", "4096"))
    unique = int(os.environ.get("FDTPU_BENCH_LEADER_UNIQUE", "768"))
    batch = int(os.environ.get("FDTPU_BENCH_LEADER_BATCH", "32"))
    tiles = int(os.environ.get("FDTPU_BENCH_LEADER_TILES", "2"))
    out = {"e2e_leader_verify_tiles": tiles}

    # --- boot 1: capacity -------------------------------------------------
    runner = TopologyRunner(
        _leader_topology(count, unique, batch, tiles,
                         rate_tps=0.0).build()).start()
    try:
        runner.wait_running(timeout_s=840)
        t0 = time.perf_counter()
        runner.wait_idle("synth", "tx", count, timeout_s=600)
        _leader_wait_drained(runner, count, tiles)
        wall = time.perf_counter() - t0
        txns = runner.metrics("bank0")["txns"]
        out["e2e_leader_tps"] = round(txns / wall, 1) if wall else 0.0
        out["e2e_leader_count"] = txns
        out["e2e_leader_wall_s"] = round(wall, 2)
    finally:
        runner.halt()
        runner.close()

    # --- boot 2: one ramped boot for the whole sweep ----------------------
    fracs_env = os.environ.get("FDTPU_BENCH_LEADER_SWEEP",
                               "0.5,0.8,1.2")
    fracs = [float(f) for f in fracs_env.split(",") if f.strip()]
    cap = out["e2e_leader_tps"]
    if fracs and cap > 0:
        dur = float(os.environ.get("FDTPU_BENCH_LEADER_STANZA_S",
                                   "3.0"))
        # a warmup stanza primes the pipeline (verify latency + fill)
        # so the first MEASURED stanza isn't half cold-start; it is
        # excluded from the sweep output
        warmup = max(2.0, dur)
        ramp = [[warmup, round(cap * 0.4, 1)]] \
            + [[dur, round(cap * f, 1)] for f in fracs]
        n_ramp = int(sum(d * r for d, r in ramp)) + 64
        runner = TopologyRunner(
            _leader_topology(n_ramp, unique, batch, tiles,
                             rate_tps=ramp).build()).start()
        sweep = []
        try:
            runner.wait_running(timeout_s=840)
            # stanza clock starts when the synth's token bucket does
            # (its first publish) — poll fast for the first frag
            deadline = time.monotonic() + 60
            while runner.metrics("synth")["tx"] == 0 \
                    and time.monotonic() < deadline:
                time.sleep(0.005)
            snap = _leader_hop_snapshot(runner, tiles)
            base_txns = runner.metrics("bank0")["txns"]
            t_next = time.monotonic()
            for si, (d, offered) in enumerate(ramp):
                t_next += d
                while time.monotonic() < t_next:
                    runner.check_failures()
                    time.sleep(0.02)
                cur = _leader_hop_snapshot(runner, tiles)
                txns = runner.metrics("bank0")["txns"]
                achieved = (txns - base_txns) / d
                top, bp = _leader_hop(snap, cur, tiles)
                if si > 0:              # stanza 0 is the warmup
                    sweep.append({
                        "offered_tps": offered,
                        "achieved_tps": round(achieved, 1),
                        "served_frac": round(achieved / offered, 3)
                        if offered else 0.0,
                        "top_occupancy_tile": top,
                        "first_backpressured_link": bp,
                    })
                snap, base_txns = cur, txns
        finally:
            runner.halt()
            runner.close()
        out["e2e_leader_sweep"] = sweep
        served = [p for p in sweep if p.get("served_frac", 0) >= 0.9]
        knee = max((p["achieved_tps"] for p in served), default=None)
        out["e2e_leader_knee_tps"] = round(knee, 1) \
            if knee is not None else None
        # the saturating hop: attribution at the first point past the
        # knee (where the loop stopped keeping up), else at the top
        # offered point — the "what to fix next" pointer
        past = next((p for p in sweep
                     if p.get("served_frac", 1.0) < 0.9), None)
        at = past or (sweep[-1] if sweep else None)
        if at:
            out["e2e_leader_hop"] = {
                "top_occupancy_tile": at["top_occupancy_tile"],
                "first_backpressured_link":
                    at["first_backpressured_link"],
            }
    print(json.dumps(out))
    sys.stdout.flush()


def _exec_leader_topology(count, unique, batch, verify_tiles,
                          exec_cnt, rate_tps, tcache_depth=None):
    """The r16 leader loop: the _leader_topology shape with execution
    scaled OUT of the bank — a resolv tile ahead of pack (RESOLVED
    frames: account sets + cost precomputed) and `exec_cnt` exec tiles
    pulling conflict-free waves over rings, all sharing the shm funk
    store. The bank keeps wave scheduling / commit ordering / PoH
    handoff. pack and bank consume their feedback links unreliably —
    the exec fan-out adds a bank0->exec->bank0 leg that would
    otherwise close a reliable-consumption cycle."""
    from firedancer_tpu.disco import Topology
    if tcache_depth is None:
        tcache_depth = max(16, 1 << (max(64, int(unique)).bit_length()
                                     - 4))
    cpus = os.cpu_count() or 1
    cpu0 = 1 if cpus >= verify_tiles + exec_cnt + 7 else None
    vd = [f"vd{i}" for i in range(verify_tiles)]
    disp = [f"exec_disp{i}" for i in range(exec_cnt)]
    done = [f"exec_done{i}" for i in range(exec_cnt)]
    topo = (
        Topology(f"exl{os.getpid()}", wksp_size=1 << 27,
                 funk={"backend": "shm", "heap_mb": 16})
        .link("ingest", depth=4096, mtu=1280)
        .link("dedup_resolv", depth=4096, mtu=1280)
        .link("resolv_pack", depth=4096, mtu=2048)
        .link("pack_bank0", depth=256, mtu=16384)
        .link("bank0_done", depth=256, mtu=64)
        .link("bank0_poh", depth=256, mtu=16448)
        .link("poh_entries", depth=512, mtu=16640)
        .link("poh_slots", depth=64, mtu=64)
        .link("shreds_mirror", depth=4096, mtu=1280)
        .link("shred_req", depth=32, mtu=1280)
        .link("sign_resp", depth=32, mtu=128)
        .tcache("dedup_tc", depth=tcache_depth)
        .tile("synth", "synth", outs=["ingest"], count=count,
              unique=unique, burst=512, seed=17, rate_tps=rate_tps)
        .tile("dedup", "dedup", ins=vd, outs=["dedup_resolv"],
              tcache="dedup_tc", batch=1024)
        .tile("resolv", "resolv", ins=["dedup_resolv"],
              outs=["resolv_pack"], batch=256, fee_payer_check=False)
        .tile("pack", "pack",
              ins=["resolv_pack", ("bank0_done", False),
                   ("poh_slots", False)],
              outs=["pack_bank0"], txn_in="resolv_pack",
              resolved_in=True, bank_links=["pack_bank0"],
              done_links=["bank0_done"], slot_in="poh_slots",
              max_txn_per_microblock=31, wave=4, batch=256)
        .tile("bank0", "bank",
              ins=["pack_bank0"] + [(ln, False) for ln in done],
              outs=["bank0_done", "bank0_poh"] + disp,
              exec="svm", wave=8, poh_link="bank0_poh",
              forward_payloads=True, genesis_synth=unique,
              exec_links=disp, exec_done=done)
        .tile("poh", "poh", ins=["bank0_poh"],
              outs=["poh_entries", "poh_slots"],
              slot_link="poh_slots", hashes_per_tick=64,
              ticks_per_slot=8)
        .tile("shred", "shred", mode="leader",
              ins=["poh_entries", ("sign_resp", False)],
              outs=["shred_req", "shreds_mirror"], req="shred_req",
              resp="sign_resp", shreds_link="shreds_mirror",
              identity_hex="03a107bff3ce10be1d70dd18e74bc09967e4d63"
                           "09ba50d5f1ddc8664125531b8",
              cluster=[{"pubkey_hex": "55" * 32, "stake": 100,
                        "addr": "127.0.0.1:9"}])
        .tile("sign", "sign", ins=[("shred_req", False)],
              outs=["sign_resp"],
              seed="000102030405060708090a0b0c0d0e0f10111213141516"
                   "1718191a1b1c1d1e1f",
              clients=[{"role": "leader", "req": "shred_req",
                        "resp": "sign_resp"}])
        .tile("shredsink", "sink", ins=["shreds_mirror"]))
    for ln in disp:
        topo.link(ln, depth=64, mtu=4096)
    for ln in done:
        topo.link(ln, depth=64, mtu=64)
    for i in range(verify_tiles):
        topo.link(vd[i], depth=4096, mtu=1280)
        topo.tcache(f"vtc{i}", depth=tcache_depth)
    topo.sharded_tile(
        "verify", "verify", verify_tiles, ins=["ingest"], outs=vd,
        batch=batch, coalesce_us=500, cpu0=cpu0,
        tcache=[f"vtc{i}" for i in range(verify_tiles)])
    topo.sharded_tile("exec", "exec", exec_cnt, ins=[disp], outs=done,
                      batch=8)
    return topo


def _exec_scale_bench():
    """Execution scale-out stage (r16): one unpaced capacity boot of
    the exec-family leader loop per exec_tile_cnt — the measurement
    behind "execution scales with tile count". Per count: bank-executed
    txns per wall second (RUN -> drained) and the run's saturating-hop
    attribution (top-occupancy tile + first backpressured link) so the
    record shows WHO the bottleneck is once the bank stops executing.

    Prints one JSON line with exec_scale_tps {cnt: tps},
    exec_scale_hop {cnt: hop}, flat exec_scale_tps_N gate metrics, and
    exec_scale_leader_hop — the post-refactor leader-loop hop at 2
    exec tiles. The parent process must not touch jax."""
    sys.path.insert(0, HERE)
    from firedancer_tpu.disco import TopologyRunner
    count = int(os.environ.get("FDTPU_BENCH_EXEC_COUNT", "4096"))
    unique = int(os.environ.get("FDTPU_BENCH_EXEC_UNIQUE", "768"))
    batch = int(os.environ.get("FDTPU_BENCH_EXEC_BATCH", "32"))
    vtiles = int(os.environ.get("FDTPU_BENCH_EXEC_VERIFY_TILES", "2"))
    cnts = [int(c) for c in os.environ.get(
        "FDTPU_BENCH_EXEC_SCALE_CNTS", "1,2,4").split(",")
        if c.strip()]
    out = {"exec_scale_tps": {}, "exec_scale_hop": {},
           "exec_scale_count": count}
    for cnt in cnts:
        tiles_extra = ["resolv"] + [f"exec{i}" for i in range(cnt)]
        links_extra = ["dedup_resolv", "resolv_pack"] \
            + [f"exec_disp{i}" for i in range(cnt)] \
            + [f"exec_done{i}" for i in range(cnt)]
        runner = TopologyRunner(
            _exec_leader_topology(count, unique, batch, vtiles, cnt,
                                  rate_tps=0.0).build()).start()
        try:
            runner.wait_running(timeout_s=840)
            snap0 = _leader_hop_snapshot(runner, vtiles, tiles_extra)
            t0 = time.perf_counter()
            runner.wait_idle("synth", "tx", count, timeout_s=600)
            _leader_wait_drained(runner, count, vtiles, resolv=True)
            wall = time.perf_counter() - t0
            snap1 = _leader_hop_snapshot(runner, vtiles, tiles_extra)
            txns = runner.metrics("bank0")["txns"]
            top, bp = _leader_hop(snap0, snap1, vtiles, links_extra)
            tps = round(txns / wall, 1) if wall else 0.0
            out["exec_scale_tps"][str(cnt)] = tps
            out[f"exec_scale_tps_{cnt}"] = tps
            out["exec_scale_hop"][str(cnt)] = {
                "top_occupancy_tile": top,
                "first_backpressured_link": bp,
            }
        finally:
            runner.halt()
            runner.close()
    tps = out["exec_scale_tps"]
    if "1" in tps and "2" in tps:
        out["exec_scale_monotonic_1_2"] = tps["2"] >= tps["1"]
    hop_cnt = "2" if "2" in out["exec_scale_hop"] \
        else (str(cnts[-1]) if cnts else None)
    if hop_cnt:
        out["exec_scale_leader_hop"] = out["exec_scale_hop"][hop_cnt]
    print(json.dumps(out))
    sys.stdout.flush()


def _catchup_fixture(tmp, count, unique, n_slots, snap_slot):
    """Leader-side oracle: replay `n_slots` slots of signed transfers
    in-process, write the shm-format snapshot at `snap_slot` (atomic
    v2 checkpoint with slot + bank hash meta), archive the tail slices
    for playback, and return the per-slot bank hashes the follower
    must reproduce."""
    import struct as _struct
    from firedancer_tpu.disco.tiles import _synth_genesis
    from firedancer_tpu.funk.funk import Funk
    from firedancer_tpu.tiles.replay import InlineFanout, ReplayCore
    from firedancer_tpu.tiles.shred import pack_slice
    from firedancer_tpu.tiles.synth import make_signed_txns
    from firedancer_tpu.utils.checkpt import (CheckptWriter,
                                              snapshot_write_atomic)
    gen = _synth_genesis(unique)
    funk = Funk()
    oracle = ReplayCore(genesis=gen, verify_poh=False, funk=funk,
                        fanout=InlineFanout(funk))
    txns = make_signed_txns(count, seed=23)
    per = max(1, count // n_slots)
    slices = {}
    for s in range(1, n_slots + 1):
        batch = b""
        chunk = txns[(s - 1) * per:s * per]
        tip = hashlib.sha256(b"cu-tip-%d" % s).digest()
        batch += _struct.pack("<I", 1) + tip \
            + _struct.pack("<I", len(chunk))
        for t in chunk:
            batch += _struct.pack("<H", len(t)) + t
        slices[s] = pack_slice(s, 0, True, batch)
    snap_path = os.path.join(tmp, "snap.ckpt")
    for s in range(1, n_slots + 1):
        oracle.on_slice(slices[s])
        if s == snap_slot:
            snapshot_write_atomic(
                snap_path, oracle.funk, slot=s,
                bank_hash=oracle.bank_hash_of[s])
    tail_path = os.path.join(tmp, "tail.arch")
    with open(tail_path, "wb") as fp:
        w = CheckptWriter(fp, compress=True)
        for i, s in enumerate(range(snap_slot + 1, n_slots + 1)):
            payload = slices[s]
            w.frame(_struct.pack("<QQHI", i, s, 0, len(payload))
                    + payload)
        w.fini()
    expected = {str(s): oracle.bank_hash_of[s].hex()
                for s in range(snap_slot + 1, n_slots + 1)}
    return snap_path, tail_path, expected, oracle


def _follower_topology(snap_path, tail_path, expected, snap_slot,
                       exec_cnt):
    """The catch-up race under measurement: snapld->snapin restoring
    the shm store while playback floods the slice tail at full speed —
    the replay tile buffers behind the restore gate, then catches up
    over `exec_cnt` exec shards with the leader's bank hashes pinned."""
    from firedancer_tpu.disco import Topology
    disp = [f"exec_disp{i}" for i in range(exec_cnt)]
    done = [f"exec_done{i}" for i in range(exec_cnt)]
    topo = (
        Topology(f"cu{os.getpid()}", wksp_size=1 << 26,
                 funk={"backend": "shm", "heap_mb": 16},
                 snapshot={"path": snap_path, "min_slot": snap_slot,
                           "chunk": 4096})
        .link("snap_stream", depth=256, mtu=1 << 16)
        .link("shred_slices", depth=256, mtu=1 << 16)
        .link("replay_tower", depth=128, mtu=128)
        .tile("snapld", "snapld", outs=["snap_stream"])
        .tile("snapin", "snapin", ins=["snap_stream"])
        .tile("playback", "playback", outs=["shred_slices"],
              path=tail_path)
        .tile("replay", "replay",
              ins=["shred_slices"] + [(ln, False) for ln in done],
              outs=["replay_tower"] + disp,
              exec_links=disp, exec_done=done, wait_restore=True,
              expected=expected, verify_poh=False)
        .tile("towersink", "sink", ins=["replay_tower"]))
    for ln in disp:
        topo.link(ln, depth=64, mtu=4096)
    for ln in done:
        topo.link(ln, depth=64, mtu=64)
    topo.sharded_tile("exec", "exec", exec_cnt, ins=[disp], outs=done,
                      batch=8)
    return topo


def _catchup_bench():
    """Catch-up stage (r17): cold-start a follower from a ShmFunk
    snapshot while the slice tail streams in live, replay the tail
    over the exec tile family, and measure snapshot-load + replay
    against the in-process oracle's pinned bank hashes.

    Prints one JSON line with replay_tps (gate metric: replayed txns
    per wall second from boot to caught-up), catchup_s, the restore
    slot, and the divergence counter (must be 0). The parent process
    must not touch jax."""
    import shutil

    import jax
    sys.path.insert(0, HERE)
    from firedancer_tpu.disco import TopologyRunner

    # the in-process oracle hashes every slot's account delta; share
    # the persistent compile cache or each run re-traces lthash cold
    jax.config.update("jax_compilation_cache_dir",
                      os.path.join(HERE, ".jax_cache"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    count = int(os.environ.get("FDTPU_BENCH_CATCHUP_COUNT", "192"))
    unique = int(os.environ.get("FDTPU_BENCH_CATCHUP_UNIQUE", "16"))
    n_slots = int(os.environ.get("FDTPU_BENCH_CATCHUP_SLOTS", "12"))
    snap_slot = int(os.environ.get("FDTPU_BENCH_CATCHUP_SNAP_SLOT",
                                   "4"))
    exec_cnt = int(os.environ.get("FDTPU_BENCH_CATCHUP_EXEC_TILES",
                                  "2"))
    tmp = tempfile.mkdtemp(prefix="fdtpu_catchup_")
    snap_path, tail_path, expected, oracle = _catchup_fixture(
        tmp, count, unique, n_slots, snap_slot)
    target = n_slots - snap_slot
    runner = TopologyRunner(_follower_topology(
        snap_path, tail_path, expected, snap_slot,
        exec_cnt).build()).start()
    out = {"catchup_slots": target,
           "catchup_count": oracle.metrics["txns"]}
    try:
        runner.wait_running(timeout_s=840)
        t0 = time.perf_counter()
        deadline = t0 + 600
        m = {}
        while time.perf_counter() < deadline:
            m = runner.metrics("replay")
            if m.get("slots_replayed", 0) >= target:
                break
            time.sleep(0.05)
        wall = time.perf_counter() - t0
        if m.get("slots_replayed", 0) < target:
            raise RuntimeError(
                f"follower never caught up: "
                f"{m.get('slots_replayed', 0)}/{target} slots in "
                f"{wall:.1f}s (divergent_slot="
                f"{m.get('divergent_slot', 0)})")
        out["catchup_s"] = round(wall, 3)
        out["replay_tps"] = round(m["txns"] / wall, 1) if wall else 0.0
        out["catchup_restore_slot"] = m.get("restore_slot", 0)
        out["catchup_divergent_slot"] = m.get("divergent_slot", 0)
        out["catchup_exec_waves"] = m.get("exec_waves", 0)
    finally:
        runner.halt()
        runner.close()
        shutil.rmtree(tmp, ignore_errors=True)
    print(json.dumps(out))
    sys.stdout.flush()


def _flood_topology(shed_stakes: dict, slo_floor: float | None,
                    pool: int, rate_pps: float = 300.0):
    """The front-door topology the adversarial soak attacks: a real
    UDP sock door (per-peer policing + stake-weighted shedding,
    disco/shed.py) feeding a bulk_prefilter verify tile (RLC batch
    equation ahead of strict — tiles/verify.py r14), dedup, sink, and
    the metric tile whose SLO engine is the pass/fail judge."""
    from firedancer_tpu.disco import Topology
    slo = None
    if slo_floor is not None:
        # the judge: staked goodput at the sink must hold the floor.
        # burn_fast 1.0 = a breach means the floor was missed for the
        # WHOLE fast window — boot/drain edges and the attack-onset
        # transient (the ring briefly fills with garbage before the
        # watermark flips the door to stake-weighted shedding) don't
        # page, a SUSTAINED collapse does. The window is cpu-scaled
        # (the r11 wedge_timeout_s precedent): on a 1-2 core CI box
        # the floor is ~25 txns/window and scheduler-descheduling a
        # healthy 6-process topology for a second dents a 4 s window
        # ~20% — so small boxes judge at attack length (the criterion
        # is literally "goodput over the attack >= 80% of clean"),
        # real hosts keep the stricter 4 s acuity.
        fast_s = 4.0 if (os.cpu_count() or 1) >= 4 else 8.0
        slo = {"fast_window_s": fast_s, "slow_window_s": 20.0,
               "burn_fast": 1.0, "burn_slow": 0.5,
               "target": [{"name": "flood_goodput",
                           "expr": f"sink.rx rate > {slo_floor}/s"}]}
    topo = (
        Topology(f"flood{os.getpid()}", wksp_size=1 << 26,
                 slo=slo, flight=_bench_flight("flood"),
                 shed={"rate_pps": float(os.environ.get(
                           "FDTPU_BENCH_FLOOD_RATE_PPS", "0"))
                       or rate_pps,
                       # burst bounds the bucket-funded onset spike: a
                       # Sybil swarm's FIRST packets all ride fresh
                       # buckets (token buckets cannot police a peer
                       # that brings a new identity per burst — that
                       # is the overload gate's job), so sybils*burst
                       # is garbage the door admits before the
                       # watermark trips, every frame of it strict-
                       # kernel work stolen from staked traffic
                       "burst": 4, "max_peers": 64, "min_stake": 1,
                       # the hold is the overload duty cycle: each
                       # expiry is a recovery probe that re-admits one
                       # bucket-funded burst before the watermark
                       # re-trips, so floor the hold at attack length
                       # — ONE admission window per soak; recovery
                       # latency is bounded by the same expiry either
                       # way (the drain phase asserts it)
                       "overload_hold_s": 8.0,
                       "stakes": shed_stakes})
        # the ingest ring is deliberately SHALLOW: queued garbage is
        # latency the staked traffic pays behind it, and the sock
        # watermark (shed armed, credits <= depth/2) flips to
        # stake-weighted shedding while there is still room — a deep
        # ring would just buy the flood a bigger backlog to age in
        # (and every queued garbage frame is a strict dispatch the
        # verify tile owes before staked traffic behind it moves)
        .link("sock_verify", depth=32, mtu=1280)
        .link("verify_dedup", depth=1024, mtu=1280)
        .link("dedup_sink", depth=1024, mtu=1280)
        .tcache("verify_tc", depth=max(8192, 2 * pool))
        .tcache("dedup_tc", depth=max(8192, 2 * pool))
        .tile("sock", "sock", outs=["sock_verify"], port=0, batch=32)
        .tile("verify", "verify", ins=["sock_verify"],
              outs=["verify_dedup"], batch=16, tcache="verify_tc",
              # coalesce paced trickles toward full chunks: a strict
              # dispatch costs the same fixed-shape kernel whatever
              # the fill, and the prefilter engages on FULL chunks
              coalesce_us=150000,
              mode="bulk_prefilter")
        .tile("dedup", "dedup", ins=["verify_dedup"],
              outs=["dedup_sink"], tcache="dedup_tc", batch=256)
        .tile("sink", "sink", ins=["dedup_sink"], batch=256))
    if slo is not None:
        topo.tile("metric", "metric", port=0)
    if _bench_flight("flood"):
        topo.tile("flight", "flight")
    return topo


class _PacedSender:
    """Daemon thread pacing datagrams at aggregate `pps`, rotating
    round-robin over one or more bound sockets (each socket = one peer
    identity at the door). The staked client is a single socket; the
    Sybil swarm is ONE thread over `sybils` sockets — same identities
    and aggregate rate as a thread per Sybil, but without handing the
    scheduler dozens of competing sender threads on a small CI box
    (the soak judges the FRONT DOOR, not harness-side contention)."""

    def __init__(self, frames: list, port: int, pps: float,
                 sock=None, nsocks: int = 1):
        import socket as socket_mod
        import threading
        if sock is not None:
            self.socks = [sock]
        else:
            self.socks = [socket_mod.socket(socket_mod.AF_INET,
                                            socket_mod.SOCK_DGRAM)
                          for _ in range(nsocks)]
        self.frames, self.port, self.pps = frames, port, pps
        self.sent = 0
        self._stop = threading.Event()
        self._thr = threading.Thread(target=self._run, daemon=True)

    def start(self):
        self._thr.start()
        return self

    def _run(self):
        t0 = time.perf_counter()
        while not self._stop.is_set():
            budget = int((time.perf_counter() - t0) * self.pps)
            while self.sent < budget and not self._stop.is_set():
                self.socks[self.sent % len(self.socks)].sendto(
                    self.frames[self.sent % len(self.frames)],
                    ("127.0.0.1", self.port))
                self.sent += 1
            time.sleep(0.002)

    def stop(self):
        self._stop.set()
        self._thr.join(timeout=5)
        for s in self.socks:
            s.close()
        return self.sent


def _flood_bench():
    """Adversarial flood soak (r14, ROADMAP item 4): boot the
    front-door topology, measure clean staked goodput, then attack it
    with a seeded forged-sig flood at >= FLOOD_MULT x the clean rate
    from a Sybil swarm of unstaked peers — with the SLO engine as the
    judge (goodput floor 80% of clean), zero watchdog trips, and the
    per-peer table bounded. Prints one JSON line with the flood_* +
    rlc_prefilter_vps record.

    CPU note: the jnp RLC kernel bounds the whole soak at a few
    hundred tps (PERF.md flood methodology) — the numbers are small
    but the DYNAMICS (door shedding, overload duty cycle, prefilter
    chunk shedding, SLO hold) are the same ones the chip run sees;
    the witnessed-fallback carries the TPU-scale numbers."""
    import socket as socket_mod

    sys.path.insert(0, HERE)
    from firedancer_tpu.disco import TopologyRunner
    from firedancer_tpu.tiles.synth import make_signed_txns
    from firedancer_tpu.utils.chaos import attack_frames

    probe_pps = float(os.environ.get("FDTPU_BENCH_FLOOD_PROBE_PPS",
                                     "80"))
    mult = float(os.environ.get("FDTPU_BENCH_FLOOD_MULT", "4"))
    attack_s = float(os.environ.get("FDTPU_BENCH_FLOOD_S", "8"))
    sybils = int(os.environ.get("FDTPU_BENCH_FLOOD_SYBILS", "24"))
    clean_s = 6.0
    pool = int(probe_pps * (clean_s + attack_s + 40))
    txns = make_signed_txns(pool, seed=23)
    forged = attack_frames("flood_forged", 64, seed=29)

    # the staked identity binds first so its "ip:port" key can be in
    # the topology's [shed.stakes] table
    staked_sock = socket_mod.socket(socket_mod.AF_INET,
                                    socket_mod.SOCK_DGRAM)
    staked_sock.bind(("127.0.0.1", 0))
    skey = f"127.0.0.1:{staked_sock.getsockname()[1]}"
    out = {}

    def _port(runner):
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            p = runner.metrics("sock").get("port")
            if p:
                return int(p)
            time.sleep(0.05)
        raise TimeoutError("sock port never published")

    # --- boot 1: capacity probe (the clean knee of this box) --------------
    # saturating paced run: achieved goodput == the pipeline's capacity
    # on this host (on a 1-core CI box the whole 5-process topology
    # shares one CPU, so this is tens of tps; on real hosts/TPU it is
    # the strict-kernel rate — the protocol is host-relative by design)
    runner = TopologyRunner(
        _flood_topology({skey: 1000}, None, pool,
                        rate_pps=2 * probe_pps).build()).start()
    try:
        runner.wait_running(timeout_s=840)
        port = _port(runner)
        sender = _PacedSender(txns, port, probe_pps,
                              sock=staked_sock).start()
        time.sleep(2.0)                  # pipeline fill excluded
        rx0 = runner.metrics("sink")["rx"]
        t0 = time.perf_counter()
        time.sleep(clean_s)
        cap_tps = (runner.metrics("sink")["rx"] - rx0) \
            / (time.perf_counter() - t0)
        sender._stop.set()
        sender._thr.join(timeout=5)

        # staked offered rate sits WELL UNDER capacity so the clean
        # run is unsaturated (a goodput baseline measured at the knee
        # would just re-measure capacity) and the attack must steal
        # headroom to breach; the flood itself is sized against
        # CAPACITY (>= mult x the clean knee per the protocol).
        # Capped by the pre-rendered txn pool: the staked sender signs
        # host-side from a FINITE pool, and on a host fast enough that
        # 0.33*capacity outruns it the sender would wrap — every
        # replayed frame dedup-drops and the judged goodput collapses
        # for a harness reason, not a front-door one. ~120 s covers
        # the worst-case remaining protocol (clean ref + SLO wait +
        # baseline + attack + drain + exercise).
        clean_pps = max(4.0, float(os.environ.get(
            "FDTPU_BENCH_FLOOD_CLEAN_PPS", "0")) or 0.33 * cap_tps)
        clean_pps = min(clean_pps, (pool - sender.sent) / 120.0)

        # unsaturated clean REFERENCE on the same boot: the SLO floor
        # is 80% of what this host actually DELIVERS at clean_pps, not
        # 80% of the offered rate — on a loaded CI box achieved runs a
        # few % under offered and that gap would silently tighten the
        # judge's bar past the acceptance criterion ("80% of clean-run
        # goodput"). 8 s drains the saturated probe's backlog first
        # (ring + verify in-flight hold ~100 frames; at a small-box
        # capacity of ~20 tps that tail would otherwise inflate the
        # reference measurement).
        sent_clean = sender.sent
        sender = _PacedSender(txns[sent_clean:], port, clean_pps,
                              sock=staked_sock).start()
        time.sleep(8.0)
        rx0 = runner.metrics("sink")["rx"]
        t0 = time.perf_counter()
        time.sleep(4.0)
        clean_ref = (runner.metrics("sink")["rx"] - rx0) \
            / (time.perf_counter() - t0)
        sender._stop.set()
        sender._thr.join(timeout=5)
        sent_clean += sender.sent
    finally:
        runner.halt()
        runner.close()
    out["flood_capacity_tps"] = round(cap_tps, 1)
    out["flood_clean_ref_tps"] = round(clean_ref, 1)
    floor = round(0.8 * min(clean_ref, clean_pps), 1)
    attack_pps = max(mult * cap_tps, 4 * clean_pps)

    # --- boot 2: the attack, judged by the SLO engine ---------------------
    txns_b = txns[sent_clean:]
    runner = TopologyRunner(
        _flood_topology({skey: 1000}, floor, pool,
                        rate_pps=max(20.0, 3 * clean_pps))
        .build()).start()
    senders, flood = [], []
    try:
        runner.wait_running(timeout_s=840)
        port = _port(runner)
        sender = _PacedSender(txns_b, port, clean_pps,
                              sock=staked_sock).start()
        senders.append(sender)
        # let the engine see the clean floor held before attacking
        # (the boot window legitimately starts breached: rate 0)
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            if runner.metrics("metric")["slo_evals"] > 0 \
                    and runner.metrics("metric")["slo_breach"] == 0:
                break
            time.sleep(0.2)
        assert runner.metrics("metric")["slo_breach"] == 0, \
            "clean staked traffic never satisfied the SLO floor"
        pre_breaches = runner.metrics("metric")["slo_breaches"]
        # the clean-run goodput baseline, measured unsaturated
        rx0 = runner.metrics("sink")["rx"]
        t0 = time.perf_counter()
        time.sleep(4.0)
        clean_tps = (runner.metrics("sink")["rx"] - rx0) \
            / (time.perf_counter() - t0)
        out["flood_clean_tps"] = round(clean_tps, 1)

        rx0 = runner.metrics("sink")["rx"]
        t0 = time.perf_counter()
        flood = [_PacedSender(forged, port, attack_pps,
                              nsocks=sybils).start()]
        peers_peak, breach_ticks = 0, 0
        while time.perf_counter() - t0 < attack_s:
            runner.check_failures()
            m = runner.metrics("sock")
            peers_peak = max(peers_peak, m["peers"])
            if runner.metrics("metric")["slo_breach"]:
                breach_ticks += 1
            time.sleep(0.2)
        wall = time.perf_counter() - t0
        goodput = (runner.metrics("sink")["rx"] - rx0) / wall
        attack_sent = sum(s.stop() for s in flood)
        flood = []
        # drain: the attack is over (the staked sender keeps running —
        # the engine's rate floor should judge recovery under normal
        # traffic, and the exercise phase below still needs it); the
        # engine must CLEAR (recovery is part of the overload
        # contract) and no ring may be wedged
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            runner.check_failures()
            if runner.metrics("metric")["slo_breach"] == 0:
                break
            time.sleep(0.2)
        sockm = runner.metrics("sock")
        trips = sum(runner.metrics(t).get("sup_watchdog_trips", 0)
                    for t in ("sock", "verify", "dedup", "sink",
                              "metric"))
        # the ratio denominator is capped at the OFFERED clean rate:
        # the in-place clean window starts right after the boot fill,
        # so its measurement can catch queued backlog draining through
        # and read a few % above what the sender actually paced — an
        # inflated baseline would demand goodput the staked client
        # never even offered
        clean_eff = min(clean_tps, clean_pps)
        out.update({
            "flood_goodput_tps": round(goodput, 1),
            "flood_goodput_ratio": round(goodput / clean_eff, 3)
            if clean_eff else 0.0,
            "flood_offered_attack_pps": round(attack_sent / wall, 1),
            "flood_attack_mult": round(attack_sent / wall / clean_tps,
                                       2) if clean_tps else 0.0,
            "flood_shed_pct": round(100.0 * sockm["shed"]
                                    / max(1, sockm["shed"]
                                          + sockm["rx"]), 1),
            "flood_peers_peak": peers_peak,
            "flood_peers_bound": sockm["peers"] <= 64,
            "flood_slo_breaches": runner.metrics("metric")
            ["slo_breaches"] - pre_breaches,
            "flood_slo_breach_final": runner.metrics("metric")
            ["slo_breach"],
            "flood_watchdog_trips": trips,
        })

        # --- prefilter exercise (rlc_prefilter_vps) -----------------------
        # the judged numbers above are FROZEN; now deterministically
        # exercise the WIRED RLC path for its throughput stanza. A
        # well-tuned door sheds the whole soak at the socket (the
        # desired outcome!) and a PACED flood never piles the ring
        # high enough for full chunks — so after each overload hold
        # expires, BLAST one back-to-back burst from fresh Sybil
        # identities (fresh buckets admit until the ring is full,
        # ~depth frames in under a millisecond): the verify gathers go
        # full, chunks assemble at `batch` lanes, and every one of
        # them must cross the RLC equation.
        import socket as socket_mod
        for _ in range(2):
            deadline = time.monotonic() + 15
            while time.monotonic() < deadline:
                runner.check_failures()
                if runner.metrics("sock")["overload"] == 0:
                    break
                time.sleep(0.2)
            blast = [socket_mod.socket(socket_mod.AF_INET,
                                       socket_mod.SOCK_DGRAM)
                     for _ in range(sybils)]
            sent_b = 0
            for _ in range(4):           # ~4*sybils frames, instantly
                for s in blast:
                    s.sendto(forged[sent_b % len(forged)],
                             ("127.0.0.1", port))
                    sent_b += 1
            time.sleep(4.0)              # let verify chew the chunks
            for s in blast:
                s.close()
        runner.check_failures()
        verifym = runner.metrics("verify")
        out.update({
            "flood_rlc_shed": verifym["rlc_shed"],
            "flood_rlc_batches": verifym["rlc_batches"],
            "flood_rlc_lanes": verifym["rlc_lanes"],
            "flood_rlc_pass": verifym["rlc_pass"],
            "flood_verify_fail": verifym["verify_fail"],
        })
        if verifym["rlc_ns"] and verifym["rlc_lanes"] >= 32:
            # only a real measurement (attack + exercise combined):
            # two full chunks minimum — compile happened at boot and
            # every call rides the one pinned shape, so the ratio is
            # steady-state kernel time, not warmup noise; the chip run
            # sees far more lanes through the same counters
            out["rlc_prefilter_vps"] = round(
                verifym["rlc_lanes"] * 1e9 / verifym["rlc_ns"], 1)
        sender._stop.set()
        sender._thr.join(timeout=5)
        # zero falsely-accepted frags: everything at the sink is a
        # staked txn (forged/shed traffic must never land) — asserted
        # across clean + attack + drain + exercise
        assert runner.metrics("sink")["rx"] <= sender.sent + 1, \
            "forged frags reached the sink"
    finally:
        for s in flood:
            s.stop()
        runner.halt()
        runner.close()
    staked_sock.close()
    out["flood_pass"] = (out.get("flood_slo_breaches", 1) == 0
                         and out.get("flood_watchdog_trips", 1) == 0
                         and out.get("flood_peers_bound", False)
                         and out.get("flood_goodput_ratio", 0) >= 0.8)
    print(json.dumps(out))
    sys.stdout.flush()


def _autotune_bench():
    """fdtune offline sweep as a bench stage (opt-in:
    FDTPU_BENCH_AUTOTUNE=1). Drives tune/search.run_sweep with this
    file's _e2e_run as the measurement — one topology boot per knob
    point, the same harness the e2e stage trusts — and persists the
    winning vector as a provenance-stamped tuned profile next to the
    BENCH json (loadable via FDTPU_TUNED_PROFILE). The reported
    tuned_vs_default_tps is >= 1.0 by construction: the default point
    is always measured and the winner is the argmax including it."""
    from firedancer_tpu.tune import knob_space
    from firedancer_tpu.tune.profile import make_profile, save_profile
    from firedancer_tpu.tune.search import run_sweep
    count = int(os.environ.get("FDTPU_BENCH_AUTOTUNE_COUNT", "16384"))
    unique = int(os.environ.get("FDTPU_BENCH_AUTOTUNE_UNIQUE", "256"))
    points = int(os.environ.get("FDTPU_BENCH_AUTOTUNE_POINTS", "3"))
    state = os.environ.get(
        "FDTPU_BENCH_AUTOTUNE_STATE",
        os.path.join(tempfile.gettempdir(), "fdtune_sweep_state.json"))
    out_path = os.environ.get("FDTPU_TUNED_PROFILE_OUT",
                              os.path.join(HERE, "tuned_profile.json"))
    space = knob_space(None)

    def measure(pt):
        rec = _e2e_run(
            count, unique,
            batch=int(pt.get("verify_batch",
                             space["verify_batch"]["default"])),
            coalesce_us=float(pt.get("coalesce_us",
                                     space["coalesce_us"]["default"])),
            profile=False)
        return rec["e2e_tps"]

    res = run_sweep(measure, state, points=points,
                    log=lambda m: print(f"autotune: {m}",
                                        file=sys.stderr))
    doc = make_profile(res["knobs"], res["tuned_tps"],
                       res["default_tps"],
                       sweep={"count": count, "unique": unique,
                              "points": res["points"],
                              "measured": res["measured"],
                              "stage": "bench-autotune"})
    save_profile(doc, out_path)
    print(json.dumps({
        "tuned_vs_default_tps": round(res["tuned_vs_default_tps"], 4),
        "autotune_knobs": res["knobs"],
        "autotune_default_tps": round(res["default_tps"], 1),
        "autotune_tuned_tps": round(res["tuned_tps"], 1),
        "autotune_points": res["points"],
        "autotune_profile": out_path,
    }))
    sys.stdout.flush()


def _run_child(env_extra: dict, timeout_s: float,
               require_key: str | None = "metric"):
    """Spawn bench.py as a child with extra env; return the last JSON
    object line of its stdout (containing require_key, if given)."""
    env = dict(os.environ)
    env.update(env_extra)
    env.setdefault("FDTPU_BENCH_CHILD", "1")
    r = subprocess.run([sys.executable, os.path.abspath(__file__)],
                       capture_output=True, text=True, timeout=timeout_s,
                       cwd=HERE, env=env)
    for line in reversed(r.stdout.strip().splitlines()):
        try:
            d = json.loads(line)
            if isinstance(d, dict) and (require_key is None
                                        or require_key in d):
                return d
        except json.JSONDecodeError:
            continue
    raise RuntimeError(
        f"child rc={r.returncode}: {(r.stderr or r.stdout)[-300:]}")


def main():
    if os.environ.get("FDTPU_BENCH_E2E_CHILD") == "1":
        _e2e_bench()
        return
    if os.environ.get("FDTPU_BENCH_LEADER_CHILD") == "1":
        _leader_bench()
        return
    if os.environ.get("FDTPU_BENCH_FLOOD_CHILD") == "1":
        _flood_bench()
        return
    if os.environ.get("FDTPU_BENCH_EXEC_SCALE_CHILD") == "1":
        _exec_scale_bench()
        return
    if os.environ.get("FDTPU_BENCH_CATCHUP_CHILD") == "1":
        _catchup_bench()
        return
    if os.environ.get("FDTPU_BENCH_AUTOTUNE_CHILD") == "1":
        _autotune_bench()
        return
    if os.environ.get("FDTPU_BENCH_CHILD") == "1":
        _child_bench()
        return

    result = {"metric": "ed25519_verifies_per_sec", "value": 0.0,
              "unit": "verifies/s/chip", "vs_baseline": 0.0}
    errors = []
    t_tpu = float(os.environ.get("FDTPU_BENCH_TPU_TIMEOUT", "900"))
    t_cpu = float(os.environ.get("FDTPU_BENCH_CPU_TIMEOUT", "900"))
    try:
        result = _run_child({}, t_tpu)
    except Exception as e:  # noqa: BLE001 — must always emit JSON
        errors.append(f"default-backend: {e!r}"[:300])
        try:
            result = _run_child(
                {"JAX_PLATFORMS": "cpu", "FDTPU_BENCH_FORCE_CPU": "1"},
                t_cpu)
            result["platform"] = result.get("platform", "cpu") + " (fallback)"
        except Exception as e2:  # noqa: BLE001
            errors.append(f"cpu-fallback: {e2!r}"[:300])
            result["error"] = " | ".join(errors)

    # second stage: end-to-end tile pipeline TPS (VERDICT r2 item 2).
    # Only attempted when the kernel bench ran on a real device — the
    # 4-process pipeline on the CPU backend measures host contention,
    # not the framework. Failures annotate, never break the JSON line.
    if not result.get("platform") \
            or result["platform"].startswith("cpu") \
            or os.environ.get("FDTPU_BENCH_SKIP_E2E") == "1":
        result["e2e"] = "skipped"
        # tunnel-down fallback: carry the most recent DRIVER-READABLE
        # witnessed TPU record inside the official artifact, so an
        # outage never erases the chip-measured number (the r3 lesson:
        # "a perf claim that isn't in the driver artifact doesn't
        # exist"). Discovery is glob-latest over BENCH_r*_witnessed.json
        # (numeric round order), shared with fdwitness and fdbench —
        # the hardcoded filename used to go stale every round.
        if result.get("platform", "").startswith("cpu"):
            from firedancer_tpu.witness import latest_witnessed
            from firedancer_tpu.witness.provenance import lint_state
            hit = latest_witnessed(HERE, require_platform="tpu")
            lint = lint_state(HERE)
            if hit and not lint.get("clean"):
                # a witnessed number re-published from a tree that no
                # longer passes its own static gates would launder the
                # old measurement as current-state evidence
                result["witnessed_tpu_refused"] = (
                    f"tree has {lint.get('errors')} non-baseline lint "
                    f"error(s) — fix or baseline before re-embedding "
                    f"the witnessed record")
            elif hit:
                _, wit = hit
                # the embedded fallback stays the compact bare record;
                # the full fdwitness chain lives in the artifact itself
                result["witnessed_tpu"] = {
                    k: v for k, v in wit.items()
                    if k not in ("witness", "witnessed")}
    else:
        try:
            e2e = _run_child(
                {"FDTPU_BENCH_E2E_CHILD": "1"},
                float(os.environ.get("FDTPU_BENCH_E2E_TIMEOUT", "1500")),
                require_key=None)
            for k, v in e2e.items():
                if k.startswith("e2e_"):
                    result[k] = v
        except Exception as e3:  # noqa: BLE001
            result["e2e_error"] = f"{e3!r}"[:300]

    # leader-loop sweep (r13): the full pack->bank->poh->shred knee,
    # CPU-measured by design (the leader hops are host code) — runs on
    # every platform unless skipped. Failures annotate, never break.
    if os.environ.get("FDTPU_BENCH_SKIP_LEADER") != "1":
        try:
            env = {"FDTPU_BENCH_LEADER_CHILD": "1"}
            if result.get("platform", "").startswith("cpu"):
                # the kernel stage already proved the device unusable:
                # don't let every verify shard burn its warmup timeout
                # rediscovering that
                env["FDTPU_JAX_PLATFORM"] = "cpu"
                env["JAX_PLATFORMS"] = "cpu"
            ldr = _run_child(
                env,
                float(os.environ.get("FDTPU_BENCH_LEADER_TIMEOUT",
                                     "1200")),
                require_key="e2e_leader_tps")
            for k, v in ldr.items():
                if k.startswith("e2e_leader"):
                    result[k] = v
        except Exception as e4:  # noqa: BLE001
            result["e2e_leader_error"] = f"{e4!r}"[:300]

    # adversarial flood soak (r14): the front-door topology under a
    # seeded forged-sig flood, SLO engine as judge — runs on every
    # platform (CPU numbers are small but the shedding/overload/
    # prefilter dynamics are identical; PERF.md flood methodology).
    if os.environ.get("FDTPU_BENCH_SKIP_FLOOD") != "1":
        try:
            env = {"FDTPU_BENCH_FLOOD_CHILD": "1"}
            if result.get("platform", "").startswith("cpu"):
                env["FDTPU_JAX_PLATFORM"] = "cpu"
                env["JAX_PLATFORMS"] = "cpu"
            fl = _run_child(
                env,
                float(os.environ.get("FDTPU_BENCH_FLOOD_TIMEOUT",
                                     "1200")),
                require_key="flood_goodput_tps")
            for k, v in fl.items():
                if k.startswith("flood_") or k.startswith("rlc_"):
                    result[k] = v
        except Exception as e5:  # noqa: BLE001
            result["flood_error"] = f"{e5!r}"[:300]

    # execution scale-out (r16): the shm-funk leader loop with the
    # resolv + exec tile family, one capacity boot per exec_tile_cnt —
    # the proof that execution scales with tile count, plus the
    # post-refactor leader-hop attribution. CPU-measured by design
    # (the exec hops are host code). Failures annotate, never break.
    if os.environ.get("FDTPU_BENCH_SKIP_EXEC_SCALE") != "1":
        try:
            env = {"FDTPU_BENCH_EXEC_SCALE_CHILD": "1"}
            if result.get("platform", "").startswith("cpu"):
                env["FDTPU_JAX_PLATFORM"] = "cpu"
                env["JAX_PLATFORMS"] = "cpu"
            ex = _run_child(
                env,
                float(os.environ.get("FDTPU_BENCH_EXEC_SCALE_TIMEOUT",
                                     "1800")),
                require_key="exec_scale_tps")
            for k, v in ex.items():
                if k.startswith("exec_scale"):
                    result[k] = v
        except Exception as e6:  # noqa: BLE001
            result["exec_scale_error"] = f"{e6!r}"[:300]

    # follower catch-up (r17): cold-start from a ShmFunk snapshot
    # while the slice tail streams live, replay over the exec family
    # against the oracle's pinned bank hashes — the "become a
    # follower" throughput record. CPU-measured by design (restore +
    # replay hops are host code). Failures annotate, never break.
    if os.environ.get("FDTPU_BENCH_SKIP_CATCHUP") != "1":
        try:
            env = {"FDTPU_BENCH_CATCHUP_CHILD": "1"}
            if result.get("platform", "").startswith("cpu"):
                env["FDTPU_JAX_PLATFORM"] = "cpu"
                env["JAX_PLATFORMS"] = "cpu"
            cu = _run_child(
                env,
                float(os.environ.get("FDTPU_BENCH_CATCHUP_TIMEOUT",
                                     "1500")),
                require_key="replay_tps")
            for k, v in cu.items():
                if k.startswith("catchup_") or k == "replay_tps":
                    result[k] = v
        except Exception as e7:  # noqa: BLE001
            result["catchup_error"] = f"{e7!r}"[:300]

    # fdtune autotune stage (r20): OPT-IN (a full sweep is many e2e
    # boots — minutes, not seconds), unlike the skip-style stages
    # above. Runs the offline knob sweep through _e2e_run, persists
    # the tuned profile, and records tuned_vs_default_tps (gated >=
    # 1.0 by fdbench). A killed sweep resumes: the child's checkpoint
    # (FDTPU_BENCH_AUTOTUNE_STATE) survives across runs.
    if os.environ.get("FDTPU_BENCH_AUTOTUNE") == "1":
        try:
            env = {"FDTPU_BENCH_AUTOTUNE_CHILD": "1"}
            if result.get("platform", "").startswith("cpu"):
                env["FDTPU_JAX_PLATFORM"] = "cpu"
                env["JAX_PLATFORMS"] = "cpu"
            at = _run_child(
                env,
                float(os.environ.get("FDTPU_BENCH_AUTOTUNE_TIMEOUT",
                                     "1800")),
                require_key="tuned_vs_default_tps")
            for k, v in at.items():
                if k.startswith("autotune_") \
                        or k == "tuned_vs_default_tps":
                    result[k] = v
        except Exception as e8:  # noqa: BLE001
            result["autotune_error"] = f"{e8!r}"[:300]

    # multichip layout stanza (ROADMAP 1b): the same machine-readable
    # candidate-layout record dryrun_multichip prints into the
    # MULTICHIP tail, persisted as FIELDS of this round's BENCH json
    # so fdwitness/fdbench can diff layout choices round over round
    # (the measured choice itself comes from the fdwitness multichip
    # stage and rides the witnessed artifact as `multichip_choice`)
    try:
        sys.path.insert(0, HERE)
        from __graft_entry__ import multichip_layout_stanza
        # mesh size mirrors dryrun_multichip's 8-device default (this
        # parent must not touch jax to count devices itself) so the
        # BENCH field diffs cleanly against the MULTICHIP tail record
        n_dev = int(os.environ.get("FDTPU_BENCH_MULTICHIP_DEVICES",
                                   "8"))
        result["multichip_layout"] = multichip_layout_stanza(n_dev)
    except Exception as e:  # noqa: BLE001 — annotate, don't break
        result["multichip_layout_error"] = f"{e!r}"[:200]

    # bench-trend gate (fdbench): compare this round against the
    # previous BENCH json — kernel vps / e2e tps / knee regressions
    # beyond the threshold fail the run, and the printed diff says
    # which hop/frames moved (tools/fdbench for the standalone CLI).
    # With FDTPU_BENCH_PREV unset the gate defaults to the LATEST
    # committed BENCH_r*.json round and gates only the knee metrics
    # (the r13 contract: the knee never goes backwards; kernel/raw-tps
    # noise across heterogeneous rounds stays report-only).
    trend_rc = 0
    prev = os.environ.get("FDTPU_BENCH_PREV")
    knee_only = False
    if not prev:
        import glob as _glob
        rounds = sorted(_glob.glob(os.path.join(HERE, "BENCH_r*.json")))
        rounds = [r for r in rounds
                  if "witnessed" not in os.path.basename(r)]
        if rounds:
            prev = rounds[-1]
            knee_only = True
    if prev:
        try:
            from firedancer_tpu.prof.bench_diff import (
                KNEE_METRICS, diff_bench, gate_regressions, load_bench,
                render_text)
            old = load_bench(prev)
            thr = float(os.environ.get("FDTPU_BENCH_GATE_PCT", "0.05"))
            d = diff_bench(old, result)
            regs = gate_regressions(
                d, threshold=thr,
                keys=KNEE_METRICS if knee_only else None)
            print(render_text(d, regs, thr), file=sys.stderr)
            result["bench_gate"] = {
                "prev": prev, "threshold": thr,
                "knee_only": knee_only,
                "regressions": regs,
            }
            trend_rc = 1 if regs else 0
        except Exception as e:  # noqa: BLE001 — annotate, don't break
            result["bench_gate"] = {"prev": prev,
                                    "error": f"{e!r}"[:200]}
    # flight archive provenance (r19): the round's record names the
    # archive dir its stage topologies recorded into, so fdflight /
    # fdgui --archive can post-mortem the exact run behind the numbers
    if os.environ.get("FDTPU_BENCH_FLIGHT_DIR"):
        result["flight_dir"] = os.environ["FDTPU_BENCH_FLIGHT_DIR"]

    _emit_report(result)
    print(json.dumps(result))
    sys.stdout.flush()
    sys.exit(_gate_rc(result, os.environ.get("FDTPU_BENCH_GATE_E2E"))
             or trend_rc)


def _emit_report(result: dict):
    """Per-round report artifact (fdgui): FDTPU_BENCH_REPORT=<out.html>
    (any other truthy value means ./report.html) renders the
    bench-trend dashboard over every BENCH_r*.json round plus THIS
    round's record — so every CI/bench run leaves an openable report
    next to its json. Annotates `result` (report / report_error),
    never breaks the JSON line."""
    rep = os.environ.get("FDTPU_BENCH_REPORT")
    if not rep:
        return
    try:
        import glob as _glob
        out_path = rep if rep.endswith(".html") \
            else os.path.join(HERE, "report.html")
        cur = os.path.join(tempfile.gettempdir(),
                           f"BENCH_current.{os.getpid()}.json")
        with open(cur, "w") as f:
            json.dump(result, f)
        try:
            from firedancer_tpu.gui.report import report_from_bench
            from firedancer_tpu.witness import latest_witnessed
            rounds = sorted(_glob.glob(
                os.path.join(HERE, "BENCH_r*.json")))
            # witnessed artifacts chart through their own reports (and
            # the fallback embed) — as trend rounds they would double
            # up with the driver round they witness
            rounds = [r for r in rounds
                      if "witnessed" not in os.path.basename(r)]
            # provenance header panel: the latest witnessed run's
            # chain summary (git sha, device fingerprint, per-stanza
            # witnessed-vs-fallback badges) rides every bench report
            hit = latest_witnessed(HERE, require_platform=None)
            wit = hit[1] if hit else {}
            # bench_series preserves caller order, so THIS round is
            # the trajectory's last point wherever tempdir sorts
            report_from_bench(rounds + [cur], out_path,
                              witness=wit.get("witness"),
                              witnessed=wit.get("witnessed"))
        finally:
            os.unlink(cur)
        result["report"] = out_path
    except Exception as e:  # noqa: BLE001 — annotate, don't break
        result["report_error"] = f"{e!r}"[:200]


def _gate_rc(result: dict, floor: str | None) -> int:
    """Regression gate: nonzero when an e2e floor is set and the
    measured (or witnessed-fallback) e2e_tps is below it — so the
    harness can fail a PR that regresses the pipeline. A skipped e2e
    stage falls back to the witnessed record's tps; no number at all
    under a floor is itself a failure (a gate that silently passes on
    a broken bench gates nothing)."""
    if not floor:
        return 0
    tps = result.get("e2e_tps")
    if tps is None:
        tps = result.get("witnessed_tpu", {}).get("e2e_tps")
    return 0 if tps is not None and float(tps) >= float(floor) else 1


if __name__ == "__main__":
    main()
