"""Headline benchmark: batched ed25519 sigverify throughput on one chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Baseline: the reference's wiredancer FPGA sigverify tile sustains ~1M
verifies/s on one AWS-F1 card, vs ~30K/s per Skylake core for the C path
(ref: src/wiredancer/README.md:99-119). BASELINE.json's north star for this
rebuild is >= 1M ed25519 verifies/s on a single TPU chip, so
vs_baseline = verifies_per_sec / 1e6.

Methodology mirrors the reference's unit-test self-benchmarks
(ref: src/ballet/ed25519/test_ed25519.c:26-31 — print throughput from a
tight loop over pre-generated valid signatures): pre-generate distinct
signed messages host-side, tile to the microbatch size, jit-compile once,
then time steady-state iterations end-to-end (device dispatch + compute +
verdict readback).
"""
import hashlib
import json
import os
import sys
import time

import numpy as np


def _gen_vectors(n_unique: int, max_len: int, rng: np.random.Generator):
    from firedancer_tpu.utils.ed25519_ref import keypair, sign

    sig = np.zeros((n_unique, 64), np.uint8)
    pub = np.zeros((n_unique, 32), np.uint8)
    msg = np.zeros((n_unique, max_len), np.uint8)
    ln = np.zeros((n_unique,), np.int32)
    for i in range(n_unique):
        seed = hashlib.sha256(b"bench-key-%d" % (i % 8)).digest()
        m = rng.integers(0, 256, size=(int(rng.integers(32, max_len)),),
                         dtype=np.uint8).tobytes()
        _, _, pk = keypair(seed)
        s = sign(seed, m)
        sig[i] = np.frombuffer(s, np.uint8)
        pub[i] = np.frombuffer(pk, np.uint8)
        msg[i, :len(m)] = np.frombuffer(m, np.uint8)
        ln[i] = len(m)
    return sig, pub, msg, ln


def main():
    import jax
    import jax.numpy as jnp

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from firedancer_tpu.ops import ed25519 as ed

    jax.config.update(
        "jax_compilation_cache_dir",
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     ".jax_cache"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

    dev = jax.devices()[0]
    on_tpu = dev.platform != "cpu"
    batch = int(os.environ.get("FDTPU_BENCH_BATCH", "8192" if on_tpu else "64"))
    max_len = 128          # typical txn message region fits; MTU path is 1232
    n_unique = min(batch, 256)

    rng = np.random.default_rng(42)
    sig, pub, msg, ln = _gen_vectors(n_unique, max_len, rng)
    reps = -(-batch // n_unique)
    sig = np.tile(sig, (reps, 1))[:batch]
    pub = np.tile(pub, (reps, 1))[:batch]
    msg = np.tile(msg, (reps, 1))[:batch]
    ln = np.tile(ln, reps)[:batch]

    fn = jax.jit(ed.verify_batch)
    args = (jnp.asarray(sig), jnp.asarray(pub), jnp.asarray(msg),
            jnp.asarray(ln))
    out = fn(*args)
    out.block_until_ready()
    assert bool(np.asarray(out).all()), "bench vectors failed to verify"

    iters = int(os.environ.get("FDTPU_BENCH_ITERS", "8" if on_tpu else "2"))
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    out.block_until_ready()
    dt = time.perf_counter() - t0

    vps = batch * iters / dt
    print(json.dumps({
        "metric": "ed25519_verifies_per_sec",
        "value": round(vps, 1),
        "unit": "verifies/s/chip",
        "vs_baseline": round(vps / 1.0e6, 4),
    }))


if __name__ == "__main__":
    main()
